"""Self-observability loop: the database tracing itself into itself.

The reference's standalone mode imports its own telemetry so one process
is both the workload and the monitor (common/telemetry +
tracing_context.rs).  This is the zero-egress twin:

  * `statement_trace` wraps every statement's hot path in a root span
    carrying the statement fingerprint and a per-trace tail-sampling
    collector: slow or erroring statements are FORCE-kept with their full
    span tree (and land in greptime_private.slow_queries), fast clean
    ones head-sample at `trace.sample_ratio`;
  * `SelfTraceWriter` drains the exporter ring in batches through the
    normal write path into the same `opentelemetry_traces` table the OTLP
    ingest owns — so a query's trace is immediately queryable through the
    database's OWN Jaeger endpoint (servers/jaeger.py) and plain SQL;
  * `MetricScrapeTask` periodically snapshots the /metrics registry into
    the metric engine, making every `greptime_*` counter range-queryable
    with PromQL `rate()` over our own storage.

All of it is best-effort and off-safe: `trace.self = false` (default)
creates no root spans, starts no threads and restores today's behavior
bit-for-bit; a trace-write failure can never fail or slow the traced
query; and the writer runs under `tracing.suppressed()` so self-trace
writes are never themselves traced (no recursion, proven by test).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import re
import threading
import time

from . import metrics, tracing
from .errors import QueryTimeoutError, RetryLaterError
from .fault_injection import fire

_LOG = logging.getLogger("greptimedb_tpu.self_trace")

# Physical metric-engine table backing the /metrics self-scrape; each
# scraped metric becomes a logical table of the same name in `public`.
SELF_METRICS_PHYSICAL_TABLE = "greptime_self_metrics"

# Bound on spans buffered per trace: a runaway statement (thousands of
# region sub-queries) keeps the newest spans and counts the shed.
_MAX_TRACE_SPANS = 8192

_QUOTED = re.compile(r"'(?:[^']|'')*'")
_NUMBER = re.compile(r"\b\d+(?:\.\d+)?\b")
_WS = re.compile(r"\s+")


def statement_fingerprint(text: str) -> str:
    """Stable fingerprint of a statement SHAPE: literals normalized away,
    whitespace collapsed, case-folded — the key that groups 'the same
    query with different parameters' in the slow-query log and on spans
    (reference slow-query fingerprinting does the same)."""
    norm = _QUOTED.sub("?", text or "")
    norm = _NUMBER.sub("?", norm)
    norm = _WS.sub(" ", norm).strip().lower()
    return hashlib.sha1(norm.encode()).hexdigest()[:16]


class TraceCollector:
    """Per-trace span buffer for tail sampling: descendants of a collected
    root (including spans on worker threads parented explicitly) land
    here instead of the exporter; the root's finalizer decides keep/drop
    once the statement's outcome is known.  Spans finishing AFTER the
    decision (abandoned hedges) follow it: kept traces forward them to
    the exporter, dropped traces discard them."""

    __slots__ = ("_spans", "_lock", "_closed", "_kept", "dropped")

    def __init__(self):
        from collections import deque

        # deque(maxlen): O(1) drop-oldest — a runaway statement crossing
        # the cap must not pay a list shift per span under the lock on
        # the fan-out hot path (same rule as the exporter ring)
        self._spans: object = deque(maxlen=_MAX_TRACE_SPANS)
        self._lock = threading.Lock()
        self._closed = False
        self._kept = False
        self.dropped = 0

    def add(self, span):
        with self._lock:
            if self._closed:
                kept = self._kept
            else:
                if len(self._spans) >= _MAX_TRACE_SPANS:
                    self.dropped += 1
                self._spans.append(span)
                return
        if kept:
            tracing.EXPORTER.export(span)

    def close(self, keep: bool) -> list:
        with self._lock:
            self._closed = True
            self._kept = keep
            spans = list(self._spans)
            self._spans.clear()
        if self.dropped:
            metrics.TRACE_SPANS_DROPPED.inc(self.dropped)
        return spans


def _service_of(owner) -> str:
    return (
        "greptimedb_tpu.standalone"
        if hasattr(owner, "storage")
        else "greptimedb_tpu.frontend"
    )


def attach_trace_id(exc: BaseException, trace_id: str):
    """Wire the root trace id into the error surface: RETRY_LATER/timeout
    failures become one Jaeger lookup away.  The id also rides as an
    attribute so protocol layers (HTTP error JSON) can emit it as a
    field instead of parsing the message."""
    exc.trace_id = trace_id
    if (
        isinstance(exc, (RetryLaterError, QueryTimeoutError))
        and exc.args
        and isinstance(exc.args[0], str)
        and "trace_id=" not in exc.args[0]
    ):
        exc.args = (f"{exc.args[0]} [trace_id={trace_id}]",) + exc.args[1:]


@contextlib.contextmanager
def statement_trace(owner, kind: str, query_text: str, database: str = "",
                    is_promql: bool = False):
    """Root span + tail-sampling collector around one statement.

    Off (`trace.self = false`) this context manager is a pass-through —
    no span, no collector, no threads.  A statement nested inside an
    already-collected trace (INSERT ... SELECT, cursors) becomes a child
    span of the ambient trace instead of opening a second collector."""
    cfg = getattr(getattr(owner, "config", None), "trace", None)
    if cfg is None or not cfg.enabled or tracing.suppressed_active():
        yield None
        return
    fp = statement_fingerprint(query_text)
    ambient = tracing.current_span()
    if ambient is not None and ambient.collector is not None:
        with tracing.span(
            f"statement.{kind}", fingerprint=fp, db=database
        ) as s:
            yield s
        return
    ensure_started(owner)
    collector = TraceCollector()
    err: BaseException | None = None
    holder: dict = {}
    try:
        with tracing.span(
            f"statement.{kind}",
            parent=None,
            collector=collector,
            service=_service_of(owner),
            fingerprint=fp,
            db=database,
            protocol=tracing.current_protocol() or "api",
            statement=(query_text or "")[:512],
        ) as root:
            holder["root"] = root
            # registered by trace id so `extract_context` on an RPC's
            # receiving side (same process) joins THIS collector and
            # follows the tail decision — no root-less orphan rows for
            # sampled-out traces
            tracing.register_collector(root.trace_id, collector)
            yield root
    except BaseException as exc:
        err = exc
        root = holder.get("root")
        if root is not None:
            attach_trace_id(exc, root.trace_id)
        raise
    finally:
        root = holder.get("root")
        if root is not None:
            _finalize_trace(
                owner, cfg, collector, root, err, query_text, database,
                fp, is_promql,
            )


def _finalize_trace(owner, cfg, collector, root, err, query_text, database,
                    fingerprint, is_promql):
    """Tail decision at root finish: error/slow force-keep, else head
    sample.  Best-effort throughout — a failure here must never replace
    the statement's own outcome."""
    try:
        tracing.unregister_collector(root.trace_id)
        elapsed_ms = root.duration() * 1000.0
        slow = elapsed_ms >= cfg.slow_query_ms
        if err is not None:
            decision = "error"
        elif slow:
            decision = "slow"
        else:
            import random

            decision = (
                "sampled" if random.random() < cfg.sample_ratio else "dropped"
            )
        keep = decision != "dropped"
        spans = collector.close(keep)
        owner.last_trace_id = root.trace_id
        owner.last_trace_kept = keep
        metrics.TRACE_SAMPLED_TOTAL.inc(decision=decision)
        if keep:
            tracing.EXPORTER.export_batch(spans)
        # The slow-queries ROW honors the legacy slow_query section too:
        # its enable switch stays authoritative, and its threshold keeps
        # logging queries the trace threshold alone would miss (an
        # operator's slow_query.threshold_ms=100 must not silently stop
        # logging 100ms-5s queries because tracing was turned on).  The
        # row's threshold column records whichever bound fired.
        legacy = getattr(getattr(owner, "config", None), "slow_query", None)
        row_enabled = legacy is None or legacy.enable
        row_threshold_ms = (
            min(cfg.slow_query_ms, float(legacy.threshold_ms))
            if legacy is not None
            else cfg.slow_query_ms
        )
        recorder = getattr(owner, "event_recorder", None)
        if (
            recorder is not None
            and row_enabled
            and (err is not None or elapsed_ms >= row_threshold_ms)
        ):
            recorder.record_slow_query(
                query_text or "",
                int(elapsed_ms),
                int(row_threshold_ms),
                database,
                is_promql=is_promql,
                trace_id=root.trace_id,
                fingerprint=fingerprint,
                span_tree=span_tree_json(spans),
            )
    except Exception:  # noqa: BLE001 — observability never owns the outcome
        _LOG.warning("trace finalize failed", exc_info=True)


def span_tree_json(spans) -> str:
    """Compact JSON rendering of a trace's span tree (flat, start-ordered;
    parent ids stitch the hierarchy) for the slow_queries row."""
    return json.dumps(
        [
            {
                "name": s.name,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "service": s.service,
                "start_ms": int(s.start * 1000),
                "duration_ms": round(s.duration() * 1000.0, 3),
                "status": s.status,
                "attrs": s.attributes,
                "events": [e.get("name") for e in s.events],
            }
            for s in sorted(spans, key=lambda s: s.start)
        ],
        default=str,
    )


def spans_to_table(spans):
    """Finished spans -> one Arrow table in the OTLP trace-table column
    model (servers/otlp.py trace_table_schema) so the rows are
    indistinguishable from OTLP-ingested spans to the Jaeger API."""
    import pyarrow as pa

    from ..servers.otlp import trace_table_schema

    schema = trace_table_schema()
    cols: dict[str, list] = {c.name: [] for c in schema.columns}
    for s in spans:
        start_ns = int(s.start * 1_000_000_000)
        end_ns = int((s.end or s.start) * 1_000_000_000)
        cols["timestamp"].append(start_ns)
        cols["timestamp_end"].append(end_ns)
        cols["duration_nano"].append(max(0, end_ns - start_ns))
        cols["service_name"].append(s.service or "greptimedb_tpu")
        cols["trace_id"].append(s.trace_id)
        cols["span_id"].append(s.span_id)
        cols["parent_span_id"].append(s.parent_id or "")
        cols["span_kind"].append(
            "SPAN_KIND_SERVER" if s.parent_id is None else "SPAN_KIND_INTERNAL"
        )
        cols["span_name"].append(s.name)
        cols["span_status_code"].append(
            "STATUS_CODE_ERROR"
            if s.status == "ERROR"
            else ("STATUS_CODE_OK" if s.status == "OK" else "STATUS_CODE_UNSET")
        )
        cols["span_status_message"].append(s.status_message)
        cols["trace_state"].append("")
        cols["scope_name"].append("greptimedb_tpu.self_trace")
        cols["scope_version"].append("")
        cols["span_attributes"].append(json.dumps(s.attributes, default=str))
        cols["span_events"].append(json.dumps(s.events, default=str))
        cols["span_links"].append("[]")
        cols["resource_attributes"].append(
            json.dumps({"service.name": s.service or "greptimedb_tpu"})
        )
    arrays = {
        c.name: pa.array(cols[c.name], c.data_type.to_arrow())
        for c in schema.columns
    }
    return pa.table(arrays)


def _write_trace_rows(owner, table):
    """Role-adapted write of span rows into `public.opentelemetry_traces`
    through the normal ingest path (standalone: local regions + the
    system-write budget bypass; frontend: Flight fan-out)."""
    from ..servers.otlp import TRACE_TABLE_NAME, ensure_table, trace_table_schema

    if hasattr(owner, "storage"):
        ensure_table(owner, TRACE_TABLE_NAME, trace_table_schema(), "public")
        owner.insert_rows(TRACE_TABLE_NAME, table, database="public", system=True)
    else:
        owner.ensure_system_table(TRACE_TABLE_NAME, trace_table_schema(), "public")
        owner.insert_rows(TRACE_TABLE_NAME, table, database="public")


class SelfTraceWriter:
    """Background drain of the exporter ring into the own trace table.

    Best-effort by contract: a failed batch is dropped and counted
    (`greptime_self_trace_write_failures_total`), never retried into the
    hot path's way, and the whole flush runs under
    `tracing.suppressed()` so exporting traces generates no spans."""

    def __init__(self, owner, cfg):
        self.owner = owner
        self.cfg = cfg
        self._stop = threading.Event()
        self._flush_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="self-trace-writer"
        )

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(max(self.cfg.export_interval_s, 0.05)):
            if self.cfg.enabled:
                self.flush()
        if self.cfg.enabled:
            self.flush()  # final best-effort drain on close

    def flush(self) -> int:
        """Drain + write one batch synchronously; returns spans written."""
        with self._flush_lock:
            spans = tracing.EXPORTER.drain()
            if not spans:
                return 0
            with tracing.suppressed():
                try:
                    fire("trace.self_write", spans=len(spans))
                    _write_trace_rows(self.owner, spans_to_table(spans))
                except Exception:  # noqa: BLE001 — best-effort by contract
                    metrics.SELF_TRACE_WRITE_FAILURES.inc()
                    _LOG.debug(
                        "self-trace write failed; dropping %d spans",
                        len(spans), exc_info=True,
                    )
                    return 0
            metrics.SELF_TRACE_ROWS.inc(len(spans))
            return len(spans)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)


def spans_to_otlp(spans, service: str = "") -> bytes:
    """Finished `tracing.Span`s -> one OTLP ExportTraceServiceRequest
    (protobuf bytes) — the wire twin of `spans_to_table`, for roles with
    no local writer to drain into.  `service` overrides the resource
    service.name (a bare datanode's spans default to the standalone
    service label, which would misattribute them)."""
    from ..servers.otlp import OtlpSpan, encode_traces_request

    if not service:
        service = (spans[0].service if spans else "") or "greptimedb_tpu"
    out = []
    for s in spans:
        out.append(OtlpSpan(
            trace_id=s.trace_id,
            span_id=s.span_id,
            parent_span_id=s.parent_id or "",
            name=s.name,
            kind=2 if s.parent_id is None else 1,  # SERVER / INTERNAL
            start_unix_nano=int(s.start * 1_000_000_000),
            end_unix_nano=int((s.end or s.start) * 1_000_000_000),
            attrs={k: str(v) for k, v in s.attributes.items()},
            events=[
                {
                    "time_unix_nano": int(e.get("ts", 0) * 1_000_000_000),
                    "name": e.get("name", ""),
                    "attrs": {k: str(v) for k, v in e.get("attrs", {}).items()},
                }
                for e in s.events
            ],
            status_code=2 if s.status == "ERROR" else (1 if s.status == "OK" else 0),
            status_message=s.status_message,
        ))
    return encode_traces_request(
        {"service.name": service}, out,
        scope_name="greptimedb_tpu.self_trace",
    )


class OtlpExportTask:
    """OTLP/HTTP self-export for roles with NO writer path (a bare
    datanode in a multi-process cluster has regions but no SQL frontend):
    drain the exporter ring and POST protobuf trace batches to
    `trace.otlp_endpoint` — normally a frontend/standalone's own
    `/v1/otlp/v1/traces`, closing the loop so datanode spans land in the
    same `opentelemetry_traces` table as everyone else's.

    Best-effort like every self-observability path: a failed batch is
    dropped and counted, never retried into the hot path's way."""

    def __init__(self, endpoint: str, cfg=None, service: str = "",
                 interval_s: float | None = None):
        from ..remote.wire import parse_endpoints

        self.host, self.port = parse_endpoints(endpoint)[0]
        self.service = service or "greptimedb_tpu.datanode"
        self.interval_s = (
            interval_s if interval_s is not None
            else getattr(cfg, "export_interval_s", 1.0)
        )
        self._stop = threading.Event()
        self._flush_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="otlp-self-export"
        )

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(max(self.interval_s, 0.05)):
            self.flush()
        self.flush()  # final best-effort drain on close

    def flush(self) -> int:
        """Drain + POST one batch synchronously; returns spans shipped
        (0 on failure — the batch is dropped and counted)."""
        with self._flush_lock:
            spans = tracing.EXPORTER.drain()
            if not spans:
                return 0
            body = spans_to_otlp(spans, service=self.service)
            try:
                import http.client

                conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=5.0
                )
                try:
                    conn.request(
                        "POST", "/v1/otlp/v1/traces", body=body,
                        headers={"Content-Type": "application/x-protobuf"},
                    )
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status >= 400:
                        raise OSError(f"otlp export -> {resp.status}")
                finally:
                    conn.close()
            except Exception:  # noqa: BLE001 — best-effort by contract
                metrics.OTLP_SELF_EXPORT_FAILURES.inc()
                _LOG.debug(
                    "otlp self-export failed; dropping %d spans",
                    len(spans), exc_info=True,
                )
                return 0
            metrics.OTLP_SELF_EXPORT_SPANS.inc(len(spans))
            return len(spans)

    def stop(self):
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=5.0)


class MetricScrapeTask:
    """Periodic snapshot of the /metrics registry into the metric engine:
    counters/gauges verbatim, histograms expanded into Prometheus
    `_bucket`/`_sum`/`_count` series — so `rate(greptime_mito_flush_total[5m])`
    runs over OUR storage instead of an external Prometheus."""

    def __init__(self, db, cfg):
        self.db = db
        self.cfg = cfg
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="metric-self-scrape"
        )

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(max(self.cfg.scrape_interval_s, 0.05)):
            if self.cfg.enabled and self.cfg.scrape_interval_s > 0:
                self.run_once()

    def run_once(self) -> int:
        try:
            snap = metrics.REGISTRY.snapshot()
            now_ms = int(time.time() * 1000)
            rows = {
                name: [(labels, now_ms, value) for labels, value in entries]
                for name, _kind, entries in snap
            }
            with tracing.suppressed():
                n = self.db.metric.write_series_rows(
                    rows, SELF_METRICS_PHYSICAL_TABLE, "public"
                )
            metrics.SELF_SCRAPE_ROWS.inc(n)
            metrics.SELF_SCRAPE_RUNS.inc()
            return n
        except Exception:  # noqa: BLE001 — the scrape never owns the server
            _LOG.debug("metric self-scrape failed", exc_info=True)
            return 0

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)


_START_LOCK = threading.Lock()


def ensure_started(owner):
    """Idempotently start the owner's self-trace writer (and, standalone
    only, the metric scrape).  Called lazily from the first traced
    statement so tests and operators can flip `trace.self` on a live
    instance."""
    if getattr(owner, "_self_trace_writer", None) is not None:
        return owner._self_trace_writer
    with _START_LOCK:
        if getattr(owner, "_self_trace_writer", None) is None:
            cfg = owner.config.trace
            owner._self_trace_writer = SelfTraceWriter(owner, cfg).start()
            if cfg.scrape_interval_s > 0 and getattr(owner, "metric", None) is not None:
                owner._self_scrape_task = MetricScrapeTask(owner, cfg).start()
    return owner._self_trace_writer


def stop(owner):
    """Stop any self-observability threads the owner started."""
    writer = getattr(owner, "_self_trace_writer", None)
    if writer is not None:
        writer.stop()
        owner._self_trace_writer = None
    scrape = getattr(owner, "_self_scrape_task", None)
    if scrape is not None:
        scrape.stop()
        owner._self_scrape_task = None
