"""Dedicated kernel-execution thread for jax work in serving contexts.

The reference pins query execution to dedicated tokio runtimes
(common/runtime/src/global.rs:138) rather than protocol threads; we do the
same for a harder reason: the TPU PJRT plugin is not robust to first-touch
initialization from short-lived protocol handler threads (observed
`terminate called after throwing an instance of ''` aborts when jax init
raced an exiting HTTP handler thread).  All jax entry points in the serving
path submit closures here — one long-lived thread owns the backend.

Library use (tests, notebooks, bench) is unaffected: `run()` executes
inline when called from the executor thread itself or when serving mode
has not started.
"""

from __future__ import annotations

import concurrent.futures
import threading

_executor: concurrent.futures.ThreadPoolExecutor | None = None
_executor_thread_id: int | None = None
_lock = threading.Lock()


def _ensure_executor() -> concurrent.futures.ThreadPoolExecutor:
    global _executor
    with _lock:
        if _executor is None:
            _executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="gt-kernel"
            )

            def _capture_id():
                global _executor_thread_id
                _executor_thread_id = threading.get_ident()

            _executor.submit(_capture_id).result()
        return _executor


def warm_up():
    """Initialize the jax backend on the kernel thread (call once at server
    start, from the main thread)."""

    def _init():
        import jax

        jax.devices()

    _ensure_executor().submit(_init).result()


def run(fn, *args, **kwargs):
    """Run `fn` on the kernel thread (inline if already on it, or if the
    executor was never started and we're in library mode).

    The closure executes under a COPY of the caller's context so
    contextvar-based session state (Database SessionState) resolves to the
    calling connection's objects — SET/USE made inside the statement mutate
    the shared state object and stay visible to the connection."""
    if _executor is None or threading.get_ident() == _executor_thread_id:
        return fn(*args, **kwargs)
    import contextvars

    ctx = contextvars.copy_context()
    return _executor.submit(ctx.run, fn, *args, **kwargs).result()


def started() -> bool:
    return _executor is not None
