"""Cooperative per-query deadlines.

A query that cannot be served from the device tile path may fall back to
the CPU scan path, whose cost scales with raw table size — at TSBS 3-day
scale (104M rows) an unbounded Python/Arrow scan runs for minutes.  The
reference bounds runaway statements with per-request timeouts enforced in
its stream executors (servers cancel the DataFusion stream); here the
equivalent is a thread-local deadline that long-running loops check
between units of work (per SST file, per row-group batch, per plan node).

Usage:

    with deadline_scope(30.0):       # seconds; None/0 disables
        ... run the query ...

    check_deadline()                 # raises QueryTimeoutError when past

The deadline is thread-local: worker threads serving other queries are
unaffected.  Scopes nest — an inner scope can only tighten the deadline.
"""

from __future__ import annotations

import contextlib
import threading
import time

from .errors import QueryTimeoutError

_local = threading.local()


def current_deadline() -> float | None:
    """The active absolute deadline (time.monotonic seconds), or None."""
    return getattr(_local, "deadline", None)


def check_deadline():
    """Raise QueryTimeoutError when the active deadline has passed."""
    d = getattr(_local, "deadline", None)
    if d is not None and time.monotonic() > d:
        raise QueryTimeoutError(
            f"query exceeded its {getattr(_local, 'seconds', 0.0):.1f}s deadline"
        )


def propagate(fn):
    """Wrap a callable about to run on ANOTHER thread (pool workers) so it
    sees this thread's deadline: thread-locals don't cross pool.map, which
    would silently disarm the deadline on exactly the multi-region scan
    paths it exists to bound."""
    d = getattr(_local, "deadline", None)
    s = getattr(_local, "seconds", None)
    if d is None:
        return fn

    def wrapped(*args, **kwargs):
        prev = getattr(_local, "deadline", None)
        prev_s = getattr(_local, "seconds", None)
        _local.deadline = d if prev is None else min(prev, d)
        _local.seconds = s
        try:
            return fn(*args, **kwargs)
        finally:
            _local.deadline = prev
            _local.seconds = prev_s

    return wrapped


@contextlib.contextmanager
def deadline_scope(seconds: float | None):
    """Bound the enclosed work to `seconds` of wall clock.  None or <= 0
    leaves any outer deadline in force.  Nested scopes only tighten."""
    if not seconds or seconds <= 0:
        yield
        return
    prev = getattr(_local, "deadline", None)
    prev_s = getattr(_local, "seconds", None)
    new = time.monotonic() + seconds
    _local.deadline = new if prev is None else min(prev, new)
    _local.seconds = seconds if prev is None else min(prev_s or seconds, seconds)
    try:
        yield
    finally:
        _local.deadline = prev
        _local.seconds = prev_s
