"""Layered configuration: defaults -> TOML file -> environment variables.

Mirrors the reference's `Configurable::load_layered_options`
(reference src/common/config/src/config.rs:29-74): env vars use the
`GREPTIMEDB_TPU__SECTION__KEY` convention (double underscore separates
nesting levels), analogous to the reference's `GREPTIMEDB_<ROLE>__A__B`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: TOML loading degrades gracefully
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        tomllib = None  # type: ignore[assignment]

ENV_PREFIX = "GREPTIMEDB_TPU"


def _coerce(value: str, template: Any) -> Any:
    """Coerce an env-var string to the type of the default it overrides."""
    if isinstance(template, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(template, int):
        return int(value)
    if isinstance(template, float):
        return float(value)
    if isinstance(template, (list, tuple)):
        return [v.strip() for v in value.split(",")]
    return value


def _deep_merge(base: dict, overlay: dict) -> dict:
    out = dict(base)
    for k, v in overlay.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


@dataclasses.dataclass
class StorageConfig:
    data_home: str = "./greptimedb_data"
    wal_dir: str = ""  # defaults to {data_home}/wal
    sst_dir: str = ""  # defaults to {data_home}/data
    manifest_checkpoint_distance: int = 10
    write_buffer_size_mb: int = 64
    global_write_buffer_size_mb: int = 512
    memtable_time_partition_secs: int = 86400
    num_workers: int = 4
    wal_fsync: bool = False
    compaction_max_active_window_runs: int = 4
    compaction_max_inactive_window_runs: int = 1
    compaction_time_window_secs: int = 0  # 0 = infer from data
    # Budget for concurrent compaction working sets (reference
    # compaction/memory_manager.rs); oversized merges split to fit.
    compaction_memory_mb: int = 512
    # Background compaction scheduler (reference mito2 CompactionScheduler):
    # flushes nudge it, a periodic tick catches the rest.
    compaction_background_enable: bool = True
    compaction_tick_secs: float = 5.0
    # SST secondary indexes (reference mito2 `[region_engine.mito.index]`):
    index_enable: bool = True
    index_segment_rows: int = 1024  # bloom/inverted segment granularity
    index_inverted_max_terms: int = 4096  # cardinality cap for LEGACY inverted index
    # Storage-plane mirrors of the user-facing `index.*` section (engines
    # built from a bare StorageConfig see these; Config.__post_init__
    # copies the index.* knobs down, same pattern as follower_sync):
    index_segmented: bool = True
    index_segment_terms: int = 512
    index_max_terms: int = 1 << 20
    # WAL provider (reference `[wal] provider = "raft_engine" | "kafka"`):
    # "local" = per-region append logs (raft-engine analogue);
    # "shared_file" = shared-topic segmented log on wal_dir (the remote-WAL
    # interface with a file backend — point wal_dir at shared storage for
    # stateless-datanode failover); "kafka" = the wire-protocol adapter
    # over a broker (requires remote.kafka_endpoints; the offline fake in
    # remote/fake_kafka.py speaks the same framing for no-egress runs).
    wal_provider: str = "local"
    wal_num_topics: int = 4
    wal_segment_mb: int = 4
    # Object store under SSTs/manifests (reference `[storage]` with OpenDAL
    # fs/s3/gcs/oss/azblob builders).  "s3" = the SigV4 REST adapter
    # (requires remote.s3_endpoint; remote/fake_s3.py is the offline
    # twin); gcs/oss/azblob stay gated (no egress); "memory" for tests.
    store_type: str = "fs"
    # mock_remote tuning (SimulatedRemoteStore): per-op latency and
    # transient-failure injection for exercising the remote layer stack
    store_mock_latency_ms: float = 0.0
    store_mock_fail_every: int = 0
    object_cache_mb: int = 0  # >0 enables the LRU whole-object read cache
    store_retry_attempts: int = 3
    write_cache_enable: bool = False  # local staging in front of non-fs stores
    write_cache_capacity_mb: int = 512
    # Storage-plane mirror of replica.sync_interval_ms: engines built from
    # a bare StorageConfig (datanode roles) read the follower-sync cadence
    # here; Config.__post_init__ copies the replica.* knob down so the
    # user-facing surface stays `replica.sync_interval_ms`.  0 = no
    # follower tailing (open-time snapshots).
    follower_sync_interval_ms: float = 0.0
    # Storage-plane mirrors of the user-facing `ingest.*` section (same
    # copy-down pattern as index.*/replica.*): WAL group commit on the
    # region-worker loops, the flush encode pool width, and write
    # admission during an in-flight flush encode.
    ingest_group_commit: bool = True
    ingest_flush_workers: int = 2
    ingest_flush_overlap: bool = True
    # Storage-plane mirrors of the user-facing `remote.*` section (same
    # copy-down pattern): wire-adapter endpoints + shared wire-layer
    # knobs.  Engines built from a bare StorageConfig read these;
    # empty endpoints keep the in-memory/file sims.
    wal_kafka_endpoints: str = ""
    store_s3_endpoint: str = ""
    store_s3_bucket: str = "greptimedb"
    store_s3_region: str = "us-east-1"
    store_s3_access_key: str = ""
    store_s3_secret_key: str = ""
    store_s3_multipart_mb: int = 8
    remote_pool_size: int = 2
    remote_call_deadline_s: float = 5.0
    remote_connect_timeout_s: float = 2.0
    remote_retry_attempts: int = 5

    def __post_init__(self):
        # NOTE: wal_dir/sst_dir stay EMPTY unless explicitly set — they are
        # derived from data_home at USE time (effective_*), so mutating
        # data_home after construction keeps all three consistent.  Baking
        # them here made every Database whose caller set data_home late
        # share the DEFAULT ./greptimedb_data storage — colliding region
        # ids across supposedly-isolated instances (recovered the wrong
        # region's manifest; observed as cross-database data bleed in the
        # sqlness runner under load).
        pass

    def effective_wal_dir(self) -> str:
        return self.wal_dir or os.path.join(self.data_home, "wal")

    def effective_sst_dir(self) -> str:
        return self.sst_dir or os.path.join(self.data_home, "data")


@dataclasses.dataclass
class QueryConfig:
    # "tpu" lowers eligible plans to JAX kernels; "cpu" is the authoritative
    # Arrow-compute path (reference gates similarly via query.execution hooks).
    backend: str = "tpu"
    tile_rows: int = 1 << 20
    max_groups: int = 1 << 16
    # stage-1 group-space cap for hierarchical (pk x bucket) aggregation
    # (ops/aggregate.py reduce_state_axes); dense [G] states at 8 bytes make
    # 2^24 = 128 MB per tracked aggregate — fine in HBM, folded before fetch
    max_internal_groups: int = 1 << 24
    # Cost-based backend routing: lowerable plans whose post-prune row
    # estimate falls below this stay on the LOCAL CPU path — on a
    # remote-device harness every device query pays the link round-trip
    # (~100 ms here), which dwarfs a small local Arrow aggregation.
    # 0 disables routing (device path for every lowerable plan).
    tpu_min_rows: int = 0
    parallelism: int = 0  # 0 = number of local devices
    fallback_to_cpu: bool = True
    # HBM-resident SST tile cache (parallel/tile_cache.py): warm queries run
    # as one dispatch over cached device tiles instead of re-scanning Arrow.
    tile_cache_enable: bool = True
    tile_cache_mb: int = 8192
    # Rows per device chunk (pow2, multiple of the 4096-row kernel block).
    # Chunks round-robin over local devices; the multichip dryrun shrinks
    # this to drive the multi-device path with toy data.
    tile_chunk_rows: int = 1 << 24
    # Persist consolidated super-tile encodes to <data_home>/tile_cache so
    # a fresh process mmaps them instead of re-decoding/sorting (the
    # dominant cold-query cost).  Directory is set by the Database from
    # data_home; empty disables.
    tile_persist_enable: bool = True
    tile_persist_dir: str = ""
    # Region-streamed execution for working sets LARGER THAN the HBM
    # budget (parallel/tile_cache.py _streamed_execute): when the
    # estimated device planes of a query exceed tile_stream_threshold x
    # tile_cache_mb, regions build -> dispatch -> merge states -> release
    # one at a time, so peak HBM stays one region's working set (the
    # 1B-row trajectory: per-region latency is flat, total is linear).
    tile_stream_enable: bool = True
    # Stream only when the planes genuinely cannot be resident: estimates
    # below budget keep the all-at-once cached path (0.6 misfired at TSBS
    # scale — a 5.8 GB fits-fine working set streamed, so every 'warm'
    # rep re-uploaded and released everything)
    tile_stream_threshold: float = 0.9
    # Accumulation mode for tile-path sum/avg: "limb" routes them through
    # the MXU fixed-point kernel (ops/aggregate.py limb_segment_sums; one
    # batched matmul for every column).  Precision: ~1e-9 relative
    # quantization error per block; integer data is exact up to 2^29 per
    # value but loses low bits beyond that — set "float64" for exact f64
    # accumulation (per-column VPU kernels, ~6x slower at TSBS scale).
    tile_acc_dtype: str = "limb"
    # Device-side result finalization (parallel/tile_cache.py + the
    # "device_finalize" pass): recognized Sort/LIMIT/HAVING post-plans and
    # empty-group compaction run INSIDE the compiled tile program over the
    # finalized [K, G] states, so the single device->host fetch ships
    # O(rows_out) bytes (a [K, limit]/[K, top_groups] buffer + a compact
    # group-id vector) instead of O(groups).  Off restores the host
    # post-op path exactly (full-buffer fetch, CPU Sort/Limit/Having).
    device_topk: bool = True
    # Streamed device->host readback (parallel/executor.py
    # streamed_device_get): large result fetches split into
    # readback_chunk_kb-sized device_get slices with ONE slice in flight
    # while the previous one copies into the host buffer, so transfer
    # overlaps host-side decode instead of serializing ahead of it.
    # Small results (< 2 chunks) keep the single batched fetch — on a
    # remote-device link extra round-trips would cost more than the
    # overlap saves.  Off restores the one-device_get path bit-for-bit.
    streamed_readback: bool = True
    readback_chunk_kb: int = 1024
    # Per-statement wall-clock budget (seconds; 0 disables).  Enforced
    # cooperatively (utils/deadline.py): scan loops, row-group reads and
    # plan-node execution check it between units of work, so a query that
    # degrades to a full CPU scan aborts with QueryTimeoutError instead of
    # grinding unbounded (the reference cancels the DataFusion stream on
    # its request timeouts).
    timeout_s: float = 0.0
    # Named optimizer passes to switch off (query/passes.py registry) —
    # comma list via env: GREPTIMEDB_TPU__QUERY__DISABLED_PASSES=
    # "window_tile,host_fast_path".  Each strategy decision point checks
    # `passes.enabled(name, config)`, so disabling one composes with the
    # rest (the reference removes individual physical optimizer rules the
    # same way in its tests).
    disabled_passes: tuple = ()
    # Device group-by strategy (the `agg_strategy` optimizer pass,
    # parallel/tile_cache.py): "auto" picks hash vs sort per query from
    # table stats (distinct-key estimates via the segmented term index +
    # tag dictionaries vs the dense group-space size — the hash/sort
    # winner flips with group cardinality, arXiv:2411.13245); "sort"
    # forces the dense mixed-radix path (pre-hash behavior bit-for-bit);
    # "hash" forces the hash-table path wherever structurally possible.
    agg_strategy: str = "auto"
    # Auto only considers hash when the dense (padded) group space is at
    # least this large — below it dense [G] states are trivially cheap.
    agg_hash_min_group_space: int = 1 << 16
    # Hedged region reads (tail tolerance): once a region sub-query has been
    # outstanding this long, the frontend sends a duplicate to a follower
    # replica and takes whichever lands first.  0 disables hedging; it also
    # requires replica.read_followers and at least one registered follower,
    # so single-node setups are unaffected.
    hedge_delay_ms: float = 0.0
    # Once enough sub-query latencies are observed, the hedge delay adapts
    # to this percentile of recent latencies (hedge_delay_ms stays the
    # floor) — the "hedge after the p95" recipe.
    hedge_percentile: float = 0.95


@dataclasses.dataclass
class ParallelConfig:
    # Mesh axes for distributed execution: regions (data parallel over
    # devices) is the DB analogue of DP; within-host reduction rides ICI.
    mesh_shape: str = "auto"  # "auto" or e.g. "4x2"
    region_axis: str = "regions"


@dataclasses.dataclass
class ServerConfig:
    http_addr: str = "127.0.0.1:4000"
    grpc_addr: str = "127.0.0.1:4001"
    mysql_addr: str = "127.0.0.1:4002"
    postgres_addr: str = "127.0.0.1:4003"


@dataclasses.dataclass
class TelemetryConfig:
    """Anonymous usage telemetry (reference common/greptimedb-telemetry:
    version/mode/node-count every N hours unless disabled).  Default OFF;
    with no egress the report sinks to a local JSON file, where the
    reference POSTs it."""

    enable: bool = False
    interval_hours: float = 6.0
    sink_path: str = ""  # empty = <data_home>/telemetry_report.json


@dataclasses.dataclass
class TraceConfig:
    """Self-observability loop (utils/self_trace.py): end-to-end statement
    tracing exported into the database's OWN trace table, a tail-sampled
    slow-query log with full span trees, and a periodic /metrics
    self-scrape into the metric engine — the zero-egress twin of the
    reference's standalone self-monitoring (its standalone mode imports
    its own telemetry).

    Everything is off-safe: `enabled = False` (the `trace.self` knob on
    the TOML/env surface — `self` cannot be a dataclass field name)
    restores today's behavior bit-for-bit — no root statement spans, no
    writer threads, no scrape.  With it on, fast statements head-sample
    at `sample_ratio`; statements slower than `slow_query_ms` (or
    erroring) are always kept AND land in greptime_private.slow_queries
    with their span tree."""

    # TOML/env alias: `[trace] self = true` / GREPTIMEDB_TPU__TRACE__SELF.
    _ALIASES = {"self": "enabled"}

    enabled: bool = False
    # Head-sampling ratio for statements that finish fast and clean; slow
    # or erroring statements are force-kept regardless (tail sampling).
    sample_ratio: float = 0.01
    # Force-keep threshold: a statement slower than this keeps its full
    # trace and writes a slow_queries row with the span tree attached.
    slow_query_ms: float = 5000.0
    # Metric self-scrape cadence: every interval the /metrics registry is
    # snapshotted into the metric engine (database greptime_private is NOT
    # used — rows land in `public` so PromQL/TQL range queries work
    # without USE), 0 disables.  Standalone only (needs the metric engine).
    scrape_interval_s: float = 0.0
    # SelfTraceWriter drain cadence (exporter ring -> opentelemetry_traces).
    export_interval_s: float = 0.25
    # OTLP/HTTP self-export for roles with no local writer (bare
    # datanodes): spans drain to `<endpoint>/v1/otlp/v1/traces` as OTLP
    # protobuf over the wire client instead of into a local table.
    # Empty = off (standalone/frontend keep their in-process writers).
    otlp_endpoint: str = ""


@dataclasses.dataclass
class RecorderConfig:
    """Device flight recorder (utils/flight_recorder.py): every tile
    dispatch appends one bounded record — plan fingerprint + trace id,
    strategy, build mode, per-stage ms, bytes up/down, HBM snapshot and
    degrade/coalesce/retry flags — into a drop-oldest ring surfaced via
    `information_schema.device_dispatches`, EXPLAIN ANALYZE's
    device-stage split and the `/debug/tile` endpoint.

    Default ON: the steady-state cost is one thread-local dict per
    dispatch plus a handful of perf_counter reads (the tier-1 bench
    smoke pins the warm-dispatch overhead under noise).  `enabled =
    false` makes the whole surface a no-op — empty tables, coarse
    EXPLAIN totals, today's behavior bit-for-bit."""

    enabled: bool = True
    # Records kept before drop-oldest eviction (one record ≈ 600 bytes of
    # host RAM; 4096 ≈ 2.5 MB).
    ring_size: int = 4096


@dataclasses.dataclass
class SlowQueryConfig:
    """Slow-query recording (reference common/telemetry SlowQueryOptions +
    event recorder into greptime_private.slow_queries)."""

    enable: bool = True
    threshold_ms: int = 5000
    sample_ratio: float = 1.0  # record this fraction of slow queries


@dataclasses.dataclass
class BreakerConfig:
    """Per-datanode circuit breakers in the frontend's client cache
    (utils/circuit_breaker.py).  Default OFF: a single-node setup never
    pays the bookkeeping, and tests opt in explicitly."""

    enable: bool = False
    window: int = 20  # sliding window of recent call outcomes (count-based)
    min_calls: int = 5  # don't judge a node on fewer samples than this
    failure_rate: float = 0.5  # trip when failures/window >= this
    open_cooldown_s: float = 5.0  # OPEN -> HALF_OPEN after this long
    half_open_probes: int = 1  # probe budget while HALF_OPEN
    # Breaker-aware write routing: when a WRITE meets an open breaker,
    # ask the metasrv to fail the region over to a candidate (refused
    # while the node's lease is still live) and retry against the new
    # leader instead of failing fast.  Off = writes shed like reads.
    write_hedge: bool = False


@dataclasses.dataclass
class ReplicaConfig:
    """Follower read replicas: read-only opens of a region on extra
    datanodes over the shared storage, registered in the metasrv route
    table.  Default OFF — followers must be added explicitly
    (MetaClient.add_follower) or placed by the metasrv selector
    (target_followers > 0), and reads only consult them when enabled."""

    read_followers: bool = False
    # Follower freshness: every sync_interval_ms a follower replays the
    # shared-WAL tail past its applied entry id and refreshes its manifest
    # view when the leader's manifest version advanced (so compaction-
    # deleted SSTs are dropped before a hedged read trips over them).
    # 0 disables tailing entirely and restores the open-time-snapshot
    # behavior bit-for-bit.
    sync_interval_ms: float = 0.0
    # Hedge gating: the fan-out skips hedging to a follower whose reported
    # lag (ms since its last successful sync) exceeds this bound, so
    # hedged reads are bounded-staleness by contract.  0 disables gating
    # (any registered follower is hedge-eligible, today's behavior).
    max_lag_ms: float = 0.0
    # Automatic placement: the metasrv selector keeps this many followers
    # per region on distinct live datanodes — creating them on node
    # join/failover and garbage-collecting orphans on node death.
    # 0 keeps placement manual (MetaClient.add_follower only).
    target_followers: int = 0


@dataclasses.dataclass
class TileConfig:
    """HBM super-tile lifecycle knobs that are about WHEN tiles build, not
    how queries run (those live under query.*): `prewarm_on_flush` moves
    the cold-path consolidation + upload + limb quantize off the first
    query of each TSBS family and onto a background thread at flush time,
    reusing the persistent XLA compilation cache (utils/jax_env.py).
    `Database.prewarm()` is the explicit form of the same build."""

    # Build super-tiles (and limb planes) in the background after a flush
    # lands, so the first query of a family stops paying the 10-170 s cold.
    prewarm_on_flush: bool = False
    # Coalesce flush storms: a region's prewarm runs this long after its
    # LAST flush notification, not once per flush.
    prewarm_debounce_s: float = 2.0
    # Also quantize MXU limb planes during prewarm (sum/avg families).
    prewarm_limbs: bool = True
    # Restrict prewarm to these tables (empty = every tileable base table).
    prewarm_tables: tuple = ()
    # Incremental (delta) super-tile maintenance: when a flush APPENDS
    # files to a region's set, merge only the new rows into the existing
    # entry — delta encode, merge of two sorted runs (not a re-sort),
    # on-device patch of resident planes — so post-flush cold cost is
    # O(delta rows), not O(total rows).  Off restores the
    # invalidate-and-rebuild-from-scratch path bit-for-bit.
    incremental: bool = True
    # Pipelined cold build: host-encode of column N+1 overlaps the device
    # upload of column N over a small worker pool, and the tile program's
    # jit trace/compile starts from shape metadata alone, before data
    # upload finishes.  Off restores the serial encode->upload->compile
    # loop.
    pipelined_build: bool = True
    # Host consolidation workers feeding the pipelined upload (>= 1).
    build_workers: int = 2
    # Fused family cold build (parallel/tile_cache.py): query plans (and
    # prewarm) emit plane-requirement manifests; a cold grouped query of a
    # NEW family answers from the host consolidation immediately while one
    # consolidated background build — the UNION of the family's manifests
    # (decode each SST once, encode each column once, one batched upload
    # through the pipelined producer/consumer) — warms the device planes;
    # concurrent cold builds for overlapping manifests coalesce onto one
    # in-flight build future whose waiters adopt the leader's planes.
    # False restores the per-query build ladder bit-for-bit: cold-serve at
    # most once per entry, device planes built synchronously on the next
    # touch, no background builder.
    fused_build: bool = True
    # Deadline for one background fused family build (upload + limb
    # quantize + compile + priming dispatch); an expired build surfaces as
    # a failed future and waiters fall back to building solo.
    fused_build_timeout_s: float = 900.0
    # Multi-chip sharded execution (parallel/tile_cache.py mesh path):
    # N > 0 runs the single-dispatch tile program under shard_map over a
    # 1-D `regions` mesh of the first N local devices — each device scans
    # + partially aggregates its shard of the super-tile chunks and the
    # partial AggStates merge via psum/pmin/pmax collectives (hash-slot
    # tables merge by keyed scatter into a union table first), with
    # device-finalize running once post-merge so readback stays
    # O(rows_out) from one chip.  0 (default) keeps today's single-chip
    # dispatch path bit-for-bit; any collective failure degrades to that
    # path automatically (fault point `mesh.collective`).  Values above
    # the available device count are rejected at config validation.
    mesh_devices: int = 0


@dataclasses.dataclass
class TqlConfig:
    """Warm TQL hot path (query/promql/tile_exec.py, the `tql_tile`
    optimizer pass): PromQL range-vector evaluation — rate/increase/
    delta, *_over_time, and the by-label sum/avg/min/max/count fold —
    runs as ONE fused dispatch over the device tile cache, sharing the
    SQL path's plane manifests, fused background builds, delta-extend
    and build coalescing.  Programs are cached per padded (series,
    steps, windows-per-sample) shape bucket with the grid and matcher
    literals as dynamic inputs, so a sliding dashboard re-hits the
    compile cache with zero host->device plane traffic.

    `tile = False` restores the legacy upload-per-query evaluation
    bit-for-bit; ANY tile-path failure (fault point `tql.tile`) degrades
    to that path too (`greptime_tql_tile_degraded_total`)."""

    tile: bool = True
    # Upper bound on padded series x padded steps cells per evaluation
    # ([S, W] f64 window-stat planes live on device); beyond it the
    # query stays on the legacy path.
    max_cells: int = 1 << 22
    # Per-series results larger than this fetch in TWO round-trips:
    # presence first, then a device-side gather of only the present
    # rows — the compacted [series_out, steps] readback.  Below it one
    # batched round-trip wins (RTT-bound, not byte-bound).
    compact_readback_kb: int = 1024


@dataclasses.dataclass
class IndexConfig:
    """Segmented term index (greptimedb_tpu/index/): new SSTs write their
    inverted/fulltext term indexes as fence-keyed term segments with
    per-segment puffin blobs, so a term lookup is binary search over
    in-memory fence keys + ONE ranged read of one segment — O(log terms)
    time, O(segment) memory, no cardinality cap below `max_terms`.

    `segmented = False` restores the legacy whole-blob formats for new
    SSTs bit-for-bit (including the 4096-term inverted cap); sidecars of
    EITHER vintage stay readable — the read router handles both."""

    segmented: bool = True
    # Terms per segment blob: the unit of both lookup memory and ranged
    # read size.  512 terms ≈ 10-40 KB per segment at typical tag widths.
    segment_terms: int = 512
    # Hard cardinality ceiling for building a term index at all (beyond
    # it the column keeps only its bloom filters).  High on purpose: the
    # segmented format is built FOR high cardinality.
    max_terms: int = 1 << 20


@dataclasses.dataclass
class IngestConfig:
    """Pipelined columnar ingest (storage/worker.py + storage/wal.py +
    storage/region.py).  Everything here is off-safe: all three knobs at
    their off positions restore the pre-pipeline write path bit-for-bit
    (frame-per-write WAL bytes, serial flush encode, stall-on-flush).

    Durability note: with `group_commit` on and `storage.wal_fsync` on,
    the fsync runs once per MERGED frame, not once per write — every
    acked write is still durable (futures resolve only after the group
    frame is written and fsynced), but writes share their fsync with the
    group.  An operator who needs one fsync *syscall* per write request
    must run with `group_commit = false`."""

    # Merge each region-worker drain group into ONE WAL frame (one Arrow
    # IPC encode, one write syscall, one optional fsync) while keeping
    # per-write entry ids — replay, follower lag accounting and
    # shared-WAL pruning see the same entries as frame-per-write.  Also
    # routes single-region inserts through the worker loops so WAL
    # appends overlap the caller building its next batch.
    group_commit: bool = True
    # Flush encode pool: SSTs of one flush (one per time window) encode
    # Parquet + indexes concurrently on this many workers.  1 = the
    # serial pre-pipeline loop.
    flush_workers: int = 2
    # Admit new writes while a flush encode is in flight: freezing a
    # memtable moves its bytes out of the mutable write-buffer budget
    # into a flushing bucket, so ingest keeps running during the encode.
    # Total (mutable + flushing) stays bounded at 2x the global buffer
    # limit before writes stall.
    flush_overlap: bool = True


@dataclasses.dataclass
class FlowConfig:
    """Incremental dataflow for materialized views (flow/dataflow.py).

    `incremental = True` routes CREATE FLOW plans the operator graph can
    express — map/filter/project, count(DISTINCT), dirty-window inner
    joins, windowed heavy aggregates — through diff-driven incremental
    maintenance; plans it cannot express fall back to the periodic-batch
    engine with the reason recorded (SHOW FLOWS / EXPLAIN FLOW /
    greptime_flow_batch_fallback_total).  `incremental = False` restores
    the pre-dataflow mode selection bit-for-bit: decomposable single-table
    aggregates stream, everything else batches, joins are rejected."""

    incremental: bool = True
    # Dirty-window granularity for recompute flows whose plan has no
    # date_bin/time_bucket group key (joins/projections over raw
    # timestamps): diffs dirty ranges of this width.
    window_ms: int = 3_600_000
    # Upper bound on windows recomputed per diff batch; the overflow stays
    # dirty and is picked up by the next diff/flush (protects the insert
    # path from a single backfill batch fanning into thousands of
    # synchronous re-runs).
    max_windows_per_recompute: int = 64


@dataclasses.dataclass
class AdmissionConfig:
    """Multi-tenant admission control in front of the query/write paths
    (utils/admission.py) and the tile executor's overload machinery
    (parallel/tile_cache.py).  EVERYTHING here defaults off-safe: with
    `enable = False` (and coalesce/hbm_* off) the engine behaves
    bit-for-bit as before this layer existed."""

    # Master switch for the per-tenant weighted admission queues.
    enable: bool = False
    # Concurrent statements the scheduler admits at once.  0 falls back
    # to memory.max_concurrent_queries; if both are 0 admission never
    # queues (ordering/shedding need a finite concurrency budget).
    max_concurrent: int = 0
    # Per-tenant pending-queue cap: an arrival past this depth is shed
    # immediately with RETRY_LATER (queue-depth shedding).
    max_queue_depth: int = 64
    # Longest a query may sit queued before it is shed (wait-time
    # shedding).  Deadlined queries additionally clip to their own
    # remaining budget; 0 disables the wait bound (deadline-only).
    max_queue_wait_ms: float = 2000.0
    # Weighted fairness: "tenant:weight" pairs (e.g. "gold:4,free:1");
    # unlisted tenants get default_weight.  Weights drive a stride
    # scheduler — a weight-4 tenant drains 4x the slots of a weight-1
    # tenant under contention, and an idle tenant costs nothing.
    tenant_weights: tuple = ()
    default_weight: int = 1
    # Dispatch coalescing: concurrent queries of one family attach to a
    # single in-flight device dispatch (leader executes, waiters share
    # the finalized result — the shared-data-path idea applied across
    # concurrent queries).
    coalesce: bool = False
    # Startup allocation probe: measure REAL free device memory
    # (device.memory_stats + a touch allocation) and clamp the tile
    # budget to hbm_probe_headroom x measured-free instead of trusting
    # the configured model-based budget.
    hbm_probe: bool = False
    hbm_probe_headroom: float = 0.9
    # Closed HBM feedback loop: a RESOURCE_EXHAUSTED escaping the tile
    # path's one-shot emergency retry triggers emergency_release + a
    # halve-chunk-rows retry (down to min_chunk_rows), so forced
    # overcommit degrades to smaller dispatches instead of failing.
    hbm_retry: bool = False
    hbm_retry_attempts: int = 3
    min_chunk_rows: int = 1 << 18

    def weight_of(self, tenant: str) -> int:
        for pair in self.tenant_weights:
            name, _, w = str(pair).partition(":")
            if name == tenant:
                try:
                    return max(1, int(w))
                except ValueError:
                    return max(1, int(self.default_weight))
        return max(1, int(self.default_weight))


@dataclasses.dataclass
class BatchConfig:
    """Cross-query device batching + windowed result cache
    (parallel/batcher.py, hooked into the tile executor).  EVERYTHING
    here defaults off-safe: with `window_ms = 0` and `result_cache_mb
    = 0` the dispatch path behaves bit-for-bit as before this layer
    existed.

    Batching extends PR 6 coalescing from *identical* plans to
    *distinct* plans over the same resident table: warm queries that
    arrive within `window_ms` of each other are dispatched back-to-back
    on the device stream and their packed result buffers come home in
    ONE readback, amortizing the per-dispatch tunnel RTT across the
    batch.  Results are bit-identical to solo runs — members share the
    readback, never each other's math — and any member that cannot be
    packed degrades to its own solo dispatch."""

    # Batching window: a warm query waits up to this long for peers to
    # join its mega-dispatch.  0 disables batching entirely (today's
    # path, bit-for-bit).
    window_ms: float = 0.0
    # Most members one mega-dispatch may carry; arrivals past the cap
    # start the next batch rather than queueing behind this one.
    max_members: int = 16
    # Windowed result cache budget.  Keyed on (literal-insensitive plan
    # fingerprint + literal digest, per-region manifest version + WAL
    # tail id, bucket-aligned time window) so a sliding dashboard
    # re-serves without any dispatch; flush/delta bumps the manifest
    # version out from under stale entries.  0 disables the cache.
    result_cache_mb: int = 0
    # Mega-program fusion: the members of a batch tick compile into ONE
    # fused XLA program (shared plane scan, per-member masks/folds as
    # fused branches) keyed on the multiset of their literal-insensitive
    # program keys — one XLA invocation per tick, not per member.  Only
    # engages when batching does (window_ms > 0, single device, mesh
    # off); any trace/compile/dispatch failure degrades to the
    # per-member packed path, so False restores that path bit-for-bit.
    fuse_programs: bool = True


@dataclasses.dataclass
class MemoryConfig:
    """Admission-style memory governance (reference common/memory-manager,
    servers request_memory_limiter `max_in_flight_write_bytes`,
    `max_concurrent_queries`).  0 = unlimited."""

    max_in_flight_write_bytes: int = 0
    max_concurrent_queries: int = 0
    # Bounded-memory scans: windowed scan slices are admitted against this
    # budget (0 = unlimited), so one huge SELECT cannot OOM the process.
    max_scan_bytes: int = 0
    # Longest an UNdeadlined statement blocks for a concurrency slot
    # before degrading to RETRY_LATER (deadlined statements clip to their
    # own remaining budget; fail-fast happens only when the deadline
    # cannot absorb the expected queue wait).
    gate_wait_s: float = 5.0


@dataclasses.dataclass
class BalanceConfig:
    """Elastic balancer (distributed/balancer.py): load-driven region
    split/merge/migration driven from heartbeat RegionStats + flight-
    recorder dispatch costs.  Default OFF — with `enabled=false` the
    balancer tick is a no-op and the cluster behaves bit-for-bit as
    before this knob existed."""

    enabled: bool = False
    # EWMA smoothing factor for per-region load scores (1.0 = raw last
    # observation, no smoothing).
    ewma_alpha: float = 0.3
    # Consecutive ticks a condition (hot region / cold table / overloaded
    # node) must persist before the balancer acts — a one-tick burst can
    # never trigger a split/merge/migration.
    min_dwell_ticks: int = 3
    # Ticks a table rests after any decision before the balancer will
    # touch it again (anti-flap: a split must settle before a merge of
    # the same table can even start dwelling).
    cooldown_ticks: int = 5
    # A region is HOT when its EWMA score exceeds this absolute floor AND
    # split_hot_ratio x the mean score of its siblings.
    split_hot_score: float = 512.0
    split_hot_ratio: float = 2.0
    # A table is COLD when every region's EWMA score is below this; cold
    # multi-region tables merge down to half the partitions.
    merge_cold_score: float = 1.0
    # A datanode is OVERLOADED when its aggregate score exceeds the fleet
    # median by this ratio; its hottest region migrates to the least
    # loaded live node.
    migrate_ratio: float = 2.0
    # Split ceiling per table (the catalog's hard cap is 1024).
    max_regions_per_table: int = 16
    # Score weights: rows written since the last tick, resident memtable
    # MiB (heartbeat RegionStats), and flight-recorder device build/
    # dispatch milliseconds attributed to the region.
    write_weight: float = 1.0
    memtable_mb_weight: float = 1.0
    dispatch_ms_weight: float = 1.0


@dataclasses.dataclass
class RemoteConfig:
    """Wire-level remote backends (remote/): etcd v3 for metadata KV +
    election, Kafka for the shared WAL, S3 for the object store — each a
    real protocol client behind the same interface its in-memory sim
    implements.  Default OFF: every endpoint empty keeps the sims and
    today's behavior bit-for-bit.

    Engagement is two-knob by design: the endpoint here supplies the
    address, the existing backend selector opts the subsystem in
    (`storage.wal_provider = "kafka"`, `storage.store_type = "s3"`;
    etcd engages on the endpoint alone since the cluster KV had no
    selector).  An endpoint-less selector fails validation instead of
    silently falling back."""

    # etcd v3 gRPC-gateway endpoints ("host:port[,host:port]") for the
    # cluster metadata KV and metasrv election.  Empty = MemoryKvBackend.
    etcd_endpoints: str = ""
    # Kafka broker endpoints for the shared remote WAL; engaged together
    # with `storage.wal_provider = "kafka"`.
    kafka_endpoints: str = ""
    # S3 REST endpoint + bucket/credentials; engaged together with
    # `storage.store_type = "s3"`.
    s3_endpoint: str = ""
    s3_bucket: str = "greptimedb"
    s3_region: str = "us-east-1"
    s3_access_key: str = ""
    s3_secret_key: str = ""
    # Writes above this size go as multipart uploads.
    s3_multipart_mb: int = 8
    # Shared wire-layer knobs (all three adapters): pooled connections
    # per endpoint, per-call deadline, connect timeout, retry ladder.
    pool_size: int = 2
    call_deadline_s: float = 5.0
    connect_timeout_s: float = 2.0
    retry_attempts: int = 5


@dataclasses.dataclass
class DeviceConfig:
    """Device health supervisor (utils/device_health.py): every blocking
    device interaction (upload, compile+dispatch, readback, memory_stats
    probe, mesh collective) runs on a dedicated per-device worker thread
    under a hard deadline; a call that neither returns nor raises is
    abandoned (worker thread written off — a wedged native call cannot be
    cancelled), the device quarantines, and the query degrades down the
    existing ladder instead of hanging.  `supervised = false` restores
    direct in-thread calls bit-for-bit."""

    supervised: bool = True
    # Hard per-call deadline in seconds; each supervised call is further
    # clamped to the statement's remaining deadline budget.
    call_timeout_s: float = 30.0
    # Consecutive raised device errors (not HBM RESOURCE_EXHAUSTED — the
    # halve-and-retry ladder owns those) before a SUSPECT device
    # quarantines, breaker-style.
    error_threshold: int = 3
    # Heal prober: a QUARANTINED device re-admits only after this many
    # consecutive ghost dispatches complete within call_timeout_s.
    probe_successes: int = 3
    # Seconds between heal-probe rounds.
    probe_interval_s: float = 1.0


@dataclasses.dataclass
class Config:
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    query: QueryConfig = dataclasses.field(default_factory=QueryConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    server: ServerConfig = dataclasses.field(default_factory=ServerConfig)
    slow_query: SlowQueryConfig = dataclasses.field(default_factory=SlowQueryConfig)
    memory: MemoryConfig = dataclasses.field(default_factory=MemoryConfig)
    telemetry: TelemetryConfig = dataclasses.field(default_factory=TelemetryConfig)
    breaker: BreakerConfig = dataclasses.field(default_factory=BreakerConfig)
    replica: ReplicaConfig = dataclasses.field(default_factory=ReplicaConfig)
    tile: TileConfig = dataclasses.field(default_factory=TileConfig)
    admission: AdmissionConfig = dataclasses.field(default_factory=AdmissionConfig)
    batch: BatchConfig = dataclasses.field(default_factory=BatchConfig)
    flow: FlowConfig = dataclasses.field(default_factory=FlowConfig)
    index: IndexConfig = dataclasses.field(default_factory=IndexConfig)
    ingest: IngestConfig = dataclasses.field(default_factory=IngestConfig)
    tql: TqlConfig = dataclasses.field(default_factory=TqlConfig)
    trace: TraceConfig = dataclasses.field(default_factory=TraceConfig)
    recorder: RecorderConfig = dataclasses.field(default_factory=RecorderConfig)
    balance: BalanceConfig = dataclasses.field(default_factory=BalanceConfig)
    remote: RemoteConfig = dataclasses.field(default_factory=RemoteConfig)
    device: DeviceConfig = dataclasses.field(default_factory=DeviceConfig)

    def __post_init__(self):
        self.storage.__post_init__()
        # index.* is the user-facing surface for the segmented term index;
        # engines only see StorageConfig, so copy the knobs down — but,
        # like the replica.sync copy, only when the index knob was
        # actually engaged (moved off its default), so an explicitly-set
        # storage.index_* survives (a bare StorageConfig is the engines'
        # own config surface and tests set it directly)
        ix_defaults = IndexConfig()
        if self.index.segmented != ix_defaults.segmented:
            self.storage.index_segmented = self.index.segmented
        if self.index.segment_terms != ix_defaults.segment_terms:
            self.storage.index_segment_terms = self.index.segment_terms
        if self.index.max_terms != ix_defaults.max_terms:
            self.storage.index_max_terms = self.index.max_terms
        # replica.sync_interval_ms is the user-facing follower-tailing
        # knob; engines only see StorageConfig, so copy it down (an
        # explicitly-set storage.follower_sync_interval_ms survives when
        # the replica knob is off)
        if self.replica.sync_interval_ms > 0:
            self.storage.follower_sync_interval_ms = self.replica.sync_interval_ms
        # ingest.* is the user-facing pipelined-ingest surface; engines
        # only see StorageConfig, so copy engaged knobs down like index.*
        ing_defaults = IngestConfig()
        if self.ingest.group_commit != ing_defaults.group_commit:
            self.storage.ingest_group_commit = self.ingest.group_commit
        if self.ingest.flush_workers != ing_defaults.flush_workers:
            self.storage.ingest_flush_workers = self.ingest.flush_workers
        if self.ingest.flush_overlap != ing_defaults.flush_overlap:
            self.storage.ingest_flush_overlap = self.ingest.flush_overlap
        # remote.* is the user-facing wire-adapter surface; engines only
        # see StorageConfig, so copy engaged knobs down like index.* —
        # with every endpoint at its empty default nothing moves and the
        # storage plane stays bit-for-bit the sims
        rm, rm_defaults = self.remote, RemoteConfig()
        if rm.kafka_endpoints != rm_defaults.kafka_endpoints:
            self.storage.wal_kafka_endpoints = rm.kafka_endpoints
        if rm.s3_endpoint != rm_defaults.s3_endpoint:
            self.storage.store_s3_endpoint = rm.s3_endpoint
        if rm.s3_bucket != rm_defaults.s3_bucket:
            self.storage.store_s3_bucket = rm.s3_bucket
        if rm.s3_region != rm_defaults.s3_region:
            self.storage.store_s3_region = rm.s3_region
        if rm.s3_access_key != rm_defaults.s3_access_key:
            self.storage.store_s3_access_key = rm.s3_access_key
        if rm.s3_secret_key != rm_defaults.s3_secret_key:
            self.storage.store_s3_secret_key = rm.s3_secret_key
        if rm.s3_multipart_mb != rm_defaults.s3_multipart_mb:
            self.storage.store_s3_multipart_mb = rm.s3_multipart_mb
        if rm.pool_size != rm_defaults.pool_size:
            self.storage.remote_pool_size = rm.pool_size
        if rm.call_deadline_s != rm_defaults.call_deadline_s:
            self.storage.remote_call_deadline_s = rm.call_deadline_s
        if rm.connect_timeout_s != rm_defaults.connect_timeout_s:
            self.storage.remote_connect_timeout_s = rm.connect_timeout_s
        if rm.retry_attempts != rm_defaults.retry_attempts:
            self.storage.remote_retry_attempts = rm.retry_attempts
        self.validate()

    def validate(self):
        """Reject nonsense knob values with errors that name the knob —
        a breaker with failure_rate=0 would trip on the first blip and a
        negative hedge delay would hedge every read immediately; both are
        config mistakes, not modes."""
        from .errors import ConfigError

        dv = self.device
        if not isinstance(dv.supervised, bool):
            raise ConfigError(
                "device.supervised must be a boolean (per-device worker-"
                f"thread call supervision); got {dv.supervised!r}"
            )
        if dv.call_timeout_s <= 0:
            raise ConfigError(
                "device.call_timeout_s must be > 0 seconds (the hard "
                "deadline every supervised device call is abandoned at); "
                f"got {dv.call_timeout_s!r}"
            )
        if dv.error_threshold < 1:
            raise ConfigError(
                "device.error_threshold must be >= 1 consecutive raised "
                "device errors before quarantine; got "
                f"{dv.error_threshold!r}"
            )
        if dv.probe_successes < 1:
            raise ConfigError(
                "device.probe_successes must be >= 1 consecutive in-"
                "deadline heal probes before re-admission; got "
                f"{dv.probe_successes!r}"
            )
        if dv.probe_interval_s <= 0:
            raise ConfigError(
                "device.probe_interval_s must be > 0 seconds between "
                f"heal-probe rounds; got {dv.probe_interval_s!r}"
            )
        q, b, t, r = self.query, self.breaker, self.tile, self.replica
        if r.sync_interval_ms < 0:
            raise ConfigError(
                "replica.sync_interval_ms must be >= 0 milliseconds (0 disables "
                f"follower WAL tailing); got {r.sync_interval_ms!r}"
            )
        if r.max_lag_ms < 0:
            raise ConfigError(
                "replica.max_lag_ms must be >= 0 milliseconds (0 disables hedge "
                f"staleness gating); got {r.max_lag_ms!r}"
            )
        if (r.max_lag_ms > 0 and r.sync_interval_ms <= 0
                and self.storage.follower_sync_interval_ms <= 0):
            # a never-syncing follower's reported lag grows from open time,
            # so this combination silently gates every follower out of
            # hedging within max_lag_ms of its open — a config mistake,
            # not a mode
            raise ConfigError(
                "replica.max_lag_ms > 0 requires follower WAL tailing "
                "(replica.sync_interval_ms > 0), or every follower ages "
                f"out of hedging at its open-time snapshot; got max_lag_ms="
                f"{r.max_lag_ms!r} with sync_interval_ms="
                f"{r.sync_interval_ms!r}"
            )
        if r.target_followers < 0:
            raise ConfigError(
                "replica.target_followers must be >= 0 followers per region "
                f"(0 keeps placement manual); got {r.target_followers!r}"
            )
        if not isinstance(q.device_topk, bool):
            raise ConfigError(
                "query.device_topk must be a boolean (on-device Sort/LIMIT/"
                f"HAVING finalization); got {q.device_topk!r}"
            )
        if not isinstance(t.incremental, bool):
            raise ConfigError(
                "tile.incremental must be a boolean (delta super-tile "
                f"maintenance on flush); got {t.incremental!r}"
            )
        if not isinstance(q.streamed_readback, bool):
            raise ConfigError(
                "query.streamed_readback must be a boolean (chunked "
                f"device->host fetches overlapped with decode); got "
                f"{q.streamed_readback!r}"
            )
        if q.readback_chunk_kb < 64:
            raise ConfigError(
                "query.readback_chunk_kb must be >= 64 KiB — smaller slices "
                "pay more link round-trips than the transfer they carry; "
                f"got {q.readback_chunk_kb!r}"
            )
        if t.build_workers < 1:
            raise ConfigError(
                "tile.build_workers must be >= 1 host consolidation worker; "
                f"got {t.build_workers!r}"
            )
        if not isinstance(t.mesh_devices, int) or isinstance(t.mesh_devices, bool):
            raise ConfigError(
                "tile.mesh_devices must be an integer device count "
                f"(0 = single-chip dispatch); got {t.mesh_devices!r}"
            )
        if t.mesh_devices < 0:
            raise ConfigError(
                "tile.mesh_devices must be >= 0 devices (0 = single-chip "
                f"dispatch, N = shard over the first N); got {t.mesh_devices!r}"
            )
        if t.mesh_devices > 0:
            # reject more mesh devices than the process can see — a mesh
            # the runtime cannot build would otherwise fail at the first
            # dispatch instead of at config time (jax is already resident
            # in any process that runs queries; tolerate its absence so a
            # config-only tool can still validate the rest)
            try:
                import jax

                available = len(jax.devices())
            except Exception:  # noqa: BLE001 — no runtime: skip the bound
                available = None
            if available is not None and t.mesh_devices > available:
                raise ConfigError(
                    f"tile.mesh_devices ({t.mesh_devices}) exceeds the "
                    f"{available} available local device(s) — the regions "
                    "mesh cannot be built; lower it or raise "
                    "XLA_FLAGS=--xla_force_host_platform_device_count"
                )
        if not isinstance(t.fused_build, bool):
            raise ConfigError(
                "tile.fused_build must be a boolean (fused one-pass family "
                f"cold builds + universal cold-serve); got {t.fused_build!r}"
            )
        if t.fused_build_timeout_s <= 0:
            raise ConfigError(
                "tile.fused_build_timeout_s must be > 0 seconds (deadline "
                "for one background fused family build); got "
                f"{t.fused_build_timeout_s!r}"
            )
        if t.prewarm_debounce_s < 0:
            raise ConfigError(
                "tile.prewarm_debounce_s must be >= 0 seconds (how long after "
                f"the last flush a prewarm build starts); got {t.prewarm_debounce_s!r}"
            )
        tq = self.tql
        if not isinstance(tq.tile, bool):
            raise ConfigError(
                "tql.tile must be a boolean (warm TQL device tile path; "
                f"false = legacy upload-per-query evaluation); got {tq.tile!r}"
            )
        if not isinstance(tq.max_cells, int) or isinstance(tq.max_cells, bool) \
                or tq.max_cells < 1:
            raise ConfigError(
                "tql.max_cells must be a positive integer bound on padded "
                f"series x steps cells per evaluation; got {tq.max_cells!r}"
            )
        if not isinstance(tq.compact_readback_kb, int) \
                or isinstance(tq.compact_readback_kb, bool) \
                or tq.compact_readback_kb < 1:
            raise ConfigError(
                "tql.compact_readback_kb must be a positive size in KiB "
                "(per-series results past it fetch via the two-phase "
                f"compacted readback); got {tq.compact_readback_kb!r}"
            )
        if q.hedge_delay_ms < 0:
            raise ConfigError(
                "query.hedge_delay_ms must be >= 0 milliseconds (0 disables hedging); "
                f"got {q.hedge_delay_ms!r}"
            )
        if not (0.0 < q.hedge_percentile < 1.0):
            raise ConfigError(
                "query.hedge_percentile must be in (0, 1) — a fraction of the "
                f"latency distribution; got {q.hedge_percentile!r}"
            )
        if b.window < 1:
            raise ConfigError(
                f"breaker.window must be >= 1 recent calls; got {b.window!r}"
            )
        if b.min_calls < 1:
            raise ConfigError(
                f"breaker.min_calls must be >= 1; got {b.min_calls!r}"
            )
        if b.min_calls > b.window:
            raise ConfigError(
                f"breaker.min_calls ({b.min_calls}) cannot exceed breaker.window "
                f"({b.window}) — the window can never hold enough samples to trip"
            )
        if not (0.0 < b.failure_rate <= 1.0):
            raise ConfigError(
                "breaker.failure_rate must be in (0, 1] — the failing fraction of "
                f"the window that trips the breaker; got {b.failure_rate!r}"
            )
        if b.open_cooldown_s <= 0:
            raise ConfigError(
                "breaker.open_cooldown_s must be > 0 seconds (how long an open "
                f"breaker sheds before probing); got {b.open_cooldown_s!r}"
            )
        if b.half_open_probes < 1:
            raise ConfigError(
                f"breaker.half_open_probes must be >= 1; got {b.half_open_probes!r}"
            )
        a = self.admission
        if a.max_concurrent < 0:
            raise ConfigError(
                "admission.max_concurrent must be >= 0 statements (0 falls "
                f"back to memory.max_concurrent_queries); got {a.max_concurrent!r}"
            )
        if a.max_queue_depth < 1:
            raise ConfigError(
                "admission.max_queue_depth must be >= 1 queued statements "
                f"per tenant; got {a.max_queue_depth!r}"
            )
        if a.max_queue_wait_ms < 0:
            raise ConfigError(
                "admission.max_queue_wait_ms must be >= 0 milliseconds "
                f"(0 = deadline-bounded only); got {a.max_queue_wait_ms!r}"
            )
        if a.default_weight < 1:
            raise ConfigError(
                f"admission.default_weight must be >= 1; got {a.default_weight!r}"
            )
        for pair in a.tenant_weights:
            name, sep, w = str(pair).partition(":")
            if not sep or not name:
                raise ConfigError(
                    "admission.tenant_weights entries must be 'tenant:weight' "
                    f"pairs; got {pair!r}"
                )
            try:
                if int(w) < 1:
                    raise ValueError
            except ValueError:
                raise ConfigError(
                    "admission.tenant_weights weight must be an integer >= 1; "
                    f"got {pair!r}"
                ) from None
        if not (0.0 < a.hbm_probe_headroom <= 1.0):
            raise ConfigError(
                "admission.hbm_probe_headroom must be in (0, 1] — the "
                "fraction of measured-free HBM the tile budget may take; "
                f"got {a.hbm_probe_headroom!r}"
            )
        if a.hbm_retry_attempts < 1:
            raise ConfigError(
                "admission.hbm_retry_attempts must be >= 1 halve-and-retry "
                f"rounds; got {a.hbm_retry_attempts!r}"
            )
        if a.min_chunk_rows < 4096:
            raise ConfigError(
                "admission.min_chunk_rows must be >= 4096 (the kernel block "
                "size — halving below one block cannot help an OOM); got "
                f"{a.min_chunk_rows!r}"
            )
        bt = self.batch
        if bt.window_ms < 0:
            raise ConfigError(
                "batch.window_ms must be >= 0 milliseconds (0 disables "
                f"cross-query batching); got {bt.window_ms!r}"
            )
        if bt.max_members < 2:
            raise ConfigError(
                "batch.max_members must be >= 2 queries per mega-dispatch "
                "— a one-member batch is just a solo dispatch with extra "
                f"latency; got {bt.max_members!r}"
            )
        if bt.result_cache_mb < 0:
            raise ConfigError(
                "batch.result_cache_mb must be >= 0 MB (0 disables the "
                f"windowed result cache); got {bt.result_cache_mb!r}"
            )
        if not isinstance(bt.fuse_programs, bool):
            raise ConfigError(
                "batch.fuse_programs must be a boolean (fuse a batch "
                "tick's member programs into one XLA invocation); got "
                f"{bt.fuse_programs!r}"
            )
        ix = self.index
        if not isinstance(ix.segmented, bool):
            raise ConfigError(
                "index.segmented must be a boolean (fence-keyed segmented "
                f"term index for new SSTs); got {ix.segmented!r}"
            )
        if ix.segment_terms < 16:
            raise ConfigError(
                "index.segment_terms must be >= 16 terms per segment — "
                "smaller segments pay a ranged read per handful of terms; "
                f"got {ix.segment_terms!r}"
            )
        if ix.max_terms < ix.segment_terms:
            raise ConfigError(
                f"index.max_terms ({ix.max_terms}) cannot be below "
                f"index.segment_terms ({ix.segment_terms}) — the index "
                "could never hold even one full segment"
            )
        ing = self.ingest
        if not isinstance(ing.group_commit, bool):
            raise ConfigError(
                "ingest.group_commit must be a boolean (merge each region-"
                "worker drain group into one WAL frame; false restores "
                "frame-per-write bytes bit-for-bit — the shape to run when "
                "you need one fsync SYSCALL per write rather than per-write "
                f"durability, which group commit preserves); got "
                f"{ing.group_commit!r}"
            )
        if not isinstance(ing.flush_overlap, bool):
            raise ConfigError(
                "ingest.flush_overlap must be a boolean (admit writes while "
                f"a flush encode is in flight); got {ing.flush_overlap!r}"
            )
        if not isinstance(ing.flush_workers, int) \
                or isinstance(ing.flush_workers, bool) \
                or not (1 <= ing.flush_workers <= 64):
            raise ConfigError(
                "ingest.flush_workers must be an integer in [1, 64] — the "
                "per-flush SST encode pool width (1 = serial pre-pipeline "
                f"loop); got {ing.flush_workers!r}"
            )
        if q.agg_strategy not in ("auto", "hash", "sort"):
            raise ConfigError(
                "query.agg_strategy must be 'auto', 'hash' or 'sort' (the "
                "device group-by strategy; 'sort' restores the dense "
                f"pre-hash path bit-for-bit); got {q.agg_strategy!r}"
            )
        if q.agg_hash_min_group_space < 1024:
            raise ConfigError(
                "query.agg_hash_min_group_space must be >= 1024 groups — "
                "below that the dense path is always cheaper than a hash "
                f"table; got {q.agg_hash_min_group_space!r}"
            )
        tr = self.trace
        if not isinstance(tr.enabled, bool):
            raise ConfigError(
                "trace.self must be a boolean (self-observability loop: "
                f"statement tracing into the own trace store); got {tr.enabled!r}"
            )
        if not (0.0 <= tr.sample_ratio <= 1.0):
            raise ConfigError(
                "trace.sample_ratio must be in [0, 1] — the head-sampling "
                f"fraction for fast clean statements; got {tr.sample_ratio!r}"
            )
        if tr.slow_query_ms < 0:
            raise ConfigError(
                "trace.slow_query_ms must be >= 0 milliseconds (statements "
                "slower than this force-keep their trace and land in "
                f"slow_queries); got {tr.slow_query_ms!r}"
            )
        if tr.scrape_interval_s < 0:
            raise ConfigError(
                "trace.scrape_interval_s must be >= 0 seconds (0 disables "
                f"the /metrics self-scrape); got {tr.scrape_interval_s!r}"
            )
        if tr.export_interval_s <= 0:
            raise ConfigError(
                "trace.export_interval_s must be > 0 seconds — the "
                f"SelfTraceWriter drain cadence; got {tr.export_interval_s!r}"
            )
        rec = self.recorder
        if not isinstance(rec.enabled, bool):
            raise ConfigError(
                "recorder.enabled must be a boolean (device flight "
                "recorder behind information_schema.device_dispatches); "
                f"got {rec.enabled!r}"
            )
        if not (16 <= int(rec.ring_size) <= (1 << 20)):
            raise ConfigError(
                "recorder.ring_size must be in [16, 1048576] records — "
                "the drop-oldest ring bound of the device flight "
                f"recorder; got {rec.ring_size!r}"
            )
        fl = self.flow
        if not isinstance(fl.incremental, bool):
            raise ConfigError(
                "flow.incremental must be a boolean (diff-driven dataflow "
                f"maintenance for CREATE FLOW); got {fl.incremental!r}"
            )
        if fl.window_ms < 1:
            raise ConfigError(
                "flow.window_ms must be >= 1 millisecond — the dirty-window "
                "granularity for recompute flows without a time-bucket "
                f"group key; got {fl.window_ms!r}"
            )
        if fl.max_windows_per_recompute < 1:
            raise ConfigError(
                "flow.max_windows_per_recompute must be >= 1 window per "
                f"diff batch; got {fl.max_windows_per_recompute!r}"
            )
        bal = self.balance
        if not isinstance(bal.enabled, bool):
            raise ConfigError(
                "balance.enabled must be a boolean (elastic region "
                f"split/merge/migration tick); got {bal.enabled!r}"
            )
        if not (0.0 < bal.ewma_alpha <= 1.0):
            raise ConfigError(
                "balance.ewma_alpha must be in (0, 1] — the EWMA smoothing "
                f"factor for region load scores; got {bal.ewma_alpha!r}"
            )
        if bal.min_dwell_ticks < 1:
            raise ConfigError(
                "balance.min_dwell_ticks must be >= 1 tick — 0 would let a "
                "single burst trigger a repartition, defeating hysteresis; "
                f"got {bal.min_dwell_ticks!r}"
            )
        if bal.cooldown_ticks < 0:
            raise ConfigError(
                "balance.cooldown_ticks must be >= 0 ticks of post-decision "
                f"rest per table; got {bal.cooldown_ticks!r}"
            )
        if bal.split_hot_score <= 0:
            raise ConfigError(
                "balance.split_hot_score must be > 0 — the absolute EWMA "
                f"score floor for a hot region; got {bal.split_hot_score!r}"
            )
        if bal.split_hot_ratio < 1.0:
            raise ConfigError(
                "balance.split_hot_ratio must be >= 1 — a hot region must "
                "be at least as loaded as its mean sibling; got "
                f"{bal.split_hot_ratio!r}"
            )
        if bal.merge_cold_score < 0:
            raise ConfigError(
                "balance.merge_cold_score must be >= 0 (0 disables merges); "
                f"got {bal.merge_cold_score!r}"
            )
        if bal.migrate_ratio < 1.0:
            raise ConfigError(
                "balance.migrate_ratio must be >= 1 — the overload multiple "
                f"of the fleet median score; got {bal.migrate_ratio!r}"
            )
        if not (1 <= bal.max_regions_per_table <= 1024):
            raise ConfigError(
                "balance.max_regions_per_table must be in [1, 1024] (the "
                f"catalog region-id space per table); got "
                f"{bal.max_regions_per_table!r}"
            )
        for wname in ("write_weight", "memtable_mb_weight", "dispatch_ms_weight"):
            w = getattr(bal, wname)
            if not isinstance(w, (int, float)) or isinstance(w, bool) or w < 0:
                raise ConfigError(
                    f"balance.{wname} must be a number >= 0 (its term's "
                    f"contribution to the region load score); got {w!r}"
                )
        rm = self.remote
        for ep_name in ("etcd_endpoints", "kafka_endpoints", "s3_endpoint"):
            spec = getattr(rm, ep_name)
            if not spec:
                continue
            # parse now so a malformed address fails at config time, not
            # on the adapter's first call
            from ..remote.wire import parse_endpoints

            try:
                parse_endpoints(spec)
            except ConfigError as exc:
                raise ConfigError(
                    f"remote.{ep_name} must be host:port[,host:port]; "
                    f"got {spec!r} ({exc})"
                ) from None
        if self.storage.wal_provider == "kafka" and not (
            rm.kafka_endpoints or self.storage.wal_kafka_endpoints
        ):
            raise ConfigError(
                "storage.wal_provider = 'kafka' requires "
                "remote.kafka_endpoints (a broker address — the offline "
                "fake in remote/fake_kafka.py works); the in-memory sims "
                "stay on 'local'/'shared_file'"
            )
        if self.storage.store_type == "s3" and not (
            rm.s3_endpoint or self.storage.store_s3_endpoint
        ):
            raise ConfigError(
                "storage.store_type = 's3' requires remote.s3_endpoint "
                "(an S3 REST address — the offline fake in "
                "remote/fake_s3.py works); 'fs'/'memory' need no endpoint"
            )
        if rm.s3_endpoint and not (rm.s3_access_key and rm.s3_secret_key):
            raise ConfigError(
                "remote.s3_endpoint is set but remote.s3_access_key / "
                "remote.s3_secret_key are empty — SigV4 signing needs both"
            )
        if rm.pool_size < 1:
            raise ConfigError(
                "remote.pool_size must be >= 1 pooled connection per "
                f"endpoint; got {rm.pool_size!r}"
            )
        if rm.call_deadline_s <= 0:
            raise ConfigError(
                "remote.call_deadline_s must be > 0 seconds (the per-call "
                f"socket budget); got {rm.call_deadline_s!r}"
            )
        if rm.connect_timeout_s <= 0:
            raise ConfigError(
                "remote.connect_timeout_s must be > 0 seconds; got "
                f"{rm.connect_timeout_s!r}"
            )
        if rm.retry_attempts < 1:
            raise ConfigError(
                "remote.retry_attempts must be >= 1 total attempts; got "
                f"{rm.retry_attempts!r}"
            )
        if rm.s3_multipart_mb < 1:
            raise ConfigError(
                "remote.s3_multipart_mb must be >= 1 MiB (the multipart "
                f"upload threshold/part size); got {rm.s3_multipart_mb!r}"
            )

    @classmethod
    def load(cls, path: str | None = None, env: dict[str, str] | None = None) -> "Config":
        """defaults -> TOML at `path` -> GREPTIMEDB_TPU__SECTION__KEY env vars."""
        layers: dict = {}
        if path and os.path.exists(path):
            if tomllib is None:
                raise RuntimeError(
                    "TOML config files need Python >= 3.11 (tomllib) or the "
                    "tomli package; env-var configuration is unaffected"
                )
            with open(path, "rb") as f:
                layers = _deep_merge(layers, tomllib.load(f))
        env = env if env is not None else dict(os.environ)
        for key, val in env.items():
            if not key.startswith(ENV_PREFIX + "__"):
                continue
            parts = [p.lower() for p in key[len(ENV_PREFIX) + 2 :].split("__")]
            node: dict = layers
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = val
        return cls._from_dict(layers)

    @classmethod
    def _from_dict(cls, d: dict) -> "Config":
        cfg = cls()
        for section_field in dataclasses.fields(cls):
            section = getattr(cfg, section_field.name)
            overlay = d.get(section_field.name, {})
            if not isinstance(overlay, dict):
                continue
            # per-section key aliases (e.g. the documented `trace.self`
            # knob maps to TraceConfig.enabled — `self` cannot be a
            # dataclass field name)
            aliases = getattr(type(section), "_ALIASES", {})
            if aliases:
                overlay = {aliases.get(k, k): v for k, v in overlay.items()}
            for f in dataclasses.fields(section):
                if f.name in overlay:
                    raw = overlay[f.name]
                    default = getattr(section, f.name)
                    if isinstance(raw, str) and not isinstance(default, str):
                        raw = _coerce(raw, default)
                    setattr(section, f.name, raw)
        cfg.__post_init__()
        return cfg
