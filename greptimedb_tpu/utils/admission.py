"""Multi-tenant admission control: weighted queues, deadline-aware
dispatch, queue-depth and wait-time shedding.

This is the robustness layer that turns overload into a graceful-
degradation regime instead of a failure mode (ROADMAP open item 3).  The
flat gates in `utils/memory.py` answer "may one more statement run?";
this layer answers "WHICH statement runs next, and which should not wait
at all":

  * every tenant (database) gets its own FIFO-ish queue, drained by a
    stride scheduler — a weight-4 tenant is granted 4x the slots of a
    weight-1 tenant under contention, and an idle tenant costs nothing
    (weighted fair queueing, the classic WFQ/stride formulation);
  * within a tenant, higher `priority` runs first, then the EARLIEST
    deadline (EDF — the statement with the least slack is the one a
    FIFO would time out), then arrival order;
  * a statement whose deadline cannot absorb the EXPECTED queue wait is
    shed immediately with `RetryLaterError` (same vocabulary as the
    circuit breakers in utils/circuit_breaker.py: the client should back
    off and retry, nothing is broken) — burning queue time on a query
    that will time out anyway wastes the very resource being protected;
  * arrivals past `max_queue_depth`, and waiters past
    `max_queue_wait_ms`, are shed the same way (queue-depth and
    wait-time shedding).

Expected wait is estimated as (queued ahead + 1) / max_concurrent x an
EWMA of recent service times — deliberately crude (admission decisions
must be O(1)); the deadline comparison uses it as a LOWER bound, so the
estimate being half the true wait only delays the shed to the wait-time
bound, never breaks correctness.

Everything is off-safe: `admission.enable = False` makes `admit()` a
zero-cost pass-through, restoring pre-layer behavior bit-for-bit.

Role-equivalents in the reference: `max_concurrent_queries` +
`request_memory_limiter` are the flat gates this layer subsumes; the
deadline/priority ordering corresponds to the reference's frontend
read-preference + per-request timeout plumbing, applied at admission
time instead of after dispatch.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field

from contextlib import nullcontext as _nullcontext

from . import metrics
from .deadline import current_deadline
from .errors import RetryLaterError
from .fault_injection import fire
from .memory import SERVICE_EWMA_SEED_S, ewma_update, expected_wait_s


class AdmissionShedError(RetryLaterError):
    """Shed by the admission layer (queue depth, wait bound, or a
    deadline that cannot absorb the expected queue wait).  Subclasses
    RetryLaterError on purpose — same retryable client contract as a
    breaker trip, distinct type so tests and logs can tell them apart."""


@dataclass(order=True)
class _Waiter:
    # sort key: priority DESC (negated), earliest deadline first (None
    # sorts last via +inf), then arrival order.  seq is unique, so the
    # key never ties and the compare=False fields never participate.
    sort_key: tuple = field(init=False, repr=False)
    priority: int = field(default=0, compare=False)
    deadline: float | None = field(default=None, compare=False)
    seq: int = field(default=0, compare=False)
    event: threading.Event = field(default_factory=threading.Event, compare=False)
    admitted: bool = field(default=False, compare=False)

    def __post_init__(self):
        self.sort_key = (
            -self.priority,
            self.deadline if self.deadline is not None else float("inf"),
            self.seq,
        )


class _TenantQueue:
    def __init__(self, weight: int):
        self.weight = max(1, weight)
        self.stride = 1.0 / self.weight
        self.vpass = 0.0  # stride-scheduler virtual pass
        self.waiters: list[_Waiter] = []


class AdmissionController:
    """Per-tenant weighted admission in front of the query/write paths.

    `admit(tenant)` returns a context manager: entering either runs
    immediately (free slot, no earlier claims), queues until dispatched,
    or raises `AdmissionShedError`; exiting releases the slot and
    dispatches the next waiter.  Thread-safe; configured live through
    the shared AdmissionConfig object (tests and operators flip knobs
    at runtime, decisions read them at use time)."""

    def __init__(self, config, memory_config=None, clock=time.monotonic):
        self.config = config
        self.memory_config = memory_config
        self.clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantQueue] = {}
        self._running = 0
        self._seq = itertools.count()
        # global virtual time: the pass of the most recent grant.  Every
        # tenant is clamped up to it on touch, so neither a newcomer nor a
        # tenant returning from idle joins BEHIND the pack (a stale-low
        # vpass would monopolize dispatch until it caught up — the
        # classic stride-scheduler rejoin bug)
        self._vtime = 0.0
        # EWMA of service times feeding the expected-wait estimate
        # (shared rule set with MemoryGovernor — utils/memory.py)
        self._service_s = SERVICE_EWMA_SEED_S
        # reentrancy guard: a statement that already holds a slot must not
        # claim (or deadlock on) a second one for nested work on the same
        # thread — INSERT ... SELECT, flow mirror writes, cursor re-entry
        self._tls = threading.local()

    # ---- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "running": self._running,
                "queued": {
                    t: len(q.waiters)
                    for t, q in self._tenants.items()
                    if q.waiters
                },
                "est_service_s": self._service_s,
            }

    def _limit(self) -> int:
        limit = int(getattr(self.config, "max_concurrent", 0) or 0)
        if limit <= 0 and self.memory_config is not None:
            limit = int(getattr(self.memory_config, "max_concurrent_queries", 0) or 0)
        return limit

    # ---- scheduling core ---------------------------------------------------
    def _queued_total_locked(self) -> int:
        return sum(len(q.waiters) for q in self._tenants.values())

    def _expected_wait_s_locked(self, limit: int) -> float:
        """Lower-bound estimate of how long a NEW arrival waits for a
        slot: everyone ahead of it (plus itself) drains at `limit`
        statements per service time."""
        return expected_wait_s(
            self._service_s, self._queued_total_locked(), limit
        )

    def _tenant_locked(self, tenant: str) -> _TenantQueue:
        q = self._tenants.get(tenant)
        if q is None:
            q = self._tenants[tenant] = _TenantQueue(self.config.weight_of(tenant))
        else:
            # live weight changes (tests flip config at runtime)
            w = self.config.weight_of(tenant)
            if w != q.weight:
                q.weight = w
                q.stride = 1.0 / w
        # join (and rejoin-from-idle) at the global virtual time: a tenant
        # whose pass fell behind while it was idle must not replay its
        # missed slots against everyone else (standard stride join)
        if q.vpass < self._vtime:
            q.vpass = self._vtime
        return q

    def _dispatch_locked(self):
        """Grant freed slots to waiters: pick the non-empty tenant with
        the smallest virtual pass, pop its best waiter, wake it."""
        limit = self._limit()
        while self._running < limit:
            candidates = [
                (q.vpass, t) for t, q in self._tenants.items() if q.waiters
            ]
            if not candidates:
                return
            _, tenant = min(candidates)
            q = self._tenants[tenant]
            q.waiters.sort()
            w = q.waiters.pop(0)
            self._vtime = max(self._vtime, q.vpass)
            q.vpass += q.stride
            metrics.ADMISSION_QUEUE_DEPTH.set(len(q.waiters), tenant=tenant)
            w.admitted = True
            self._running += 1
            metrics.ADMISSION_RUNNING.set(self._running)
            w.event.set()

    def _shed(self, tenant: str, reason: str, detail: str):
        metrics.ADMISSION_SHED_TOTAL.inc(reason=reason)
        raise AdmissionShedError(
            f"admission shed ({reason}) for tenant {tenant!r}: {detail}"
        )

    # ---- public gate -------------------------------------------------------
    def admit(self, tenant: str, priority: int = 0, kind: str = "query"):
        """Context manager admitting one statement for `tenant`.

        Off (`admission.enable = False`) this is a pure pass-through —
        no lock, no metrics, no fault point."""
        import contextlib

        if not getattr(self.config, "enable", False):
            return contextlib.nullcontext()
        if getattr(self._tls, "held", 0):
            return contextlib.nullcontext()
        return self._admit_cm(tenant, priority, kind)

    def _admit_cm(self, tenant: str, priority: int, kind: str):
        import contextlib

        @contextlib.contextmanager
        def cm():
            try:
                fire("admission.shed", tenant=tenant, kind=kind)
            except BaseException as exc:
                metrics.ADMISSION_SHED_TOTAL.inc(reason="injected")
                raise exc
            t_enter = self.clock()
            # service time is measured from the GRANT, not from admit
            # entry: folding queue wait into the EWMA would inflate the
            # expected-wait estimate under congestion (more waiting ->
            # bigger estimate -> more deadline sheds, a feedback loop)
            from . import tracing

            # queue wait is a traced stage of the statement when one is
            # being traced (a shed raises through the span and is marked
            # as its error status); untraced statements skip the span
            wait_cm = (
                tracing.span("admission.wait", tenant=tenant, kind=kind)
                if tracing.current_span() is not None
                else _nullcontext()
            )
            with wait_cm as wait_span:
                t_granted = self._acquire(tenant, priority, t_enter)
                if wait_span is not None:
                    wait_span.attributes["wait_ms"] = round(
                        (t_granted - t_enter) * 1000.0, 3
                    )
            self._tls.held = getattr(self._tls, "held", 0) + 1
            try:
                yield
            finally:
                self._tls.held -= 1
                self._release(t_granted)

        return cm()

    def _acquire(self, tenant: str, priority: int, t_enter: float) -> float:
        """Block (or shed) until a slot is granted; returns the grant
        timestamp so _release charges only true service time."""
        deadline = current_deadline()
        limit = self._limit()
        waiter: _Waiter | None = None
        with self._lock:
            q = self._tenant_locked(tenant)
            if limit <= 0 or (self._running < limit and not q.waiters
                              and self._queued_total_locked() == 0):
                # free slot, nobody queued anywhere: run now (the common
                # un-contended case costs one lock round-trip)
                self._running += 1
                metrics.ADMISSION_RUNNING.set(self._running)
                metrics.ADMISSION_ADMITTED_TOTAL.inc()
                metrics.ADMISSION_WAIT_MS.observe(0.0)
                # charge the tenant's pass so bursts that alternate with
                # queueing still honor weights
                self._vtime = max(self._vtime, q.vpass)
                q.vpass += q.stride
                return self.clock()
            # ---- must queue: shed checks first -----------------------------
            if len(q.waiters) >= int(self.config.max_queue_depth):
                self._shed(
                    tenant, "queue_depth",
                    f"{len(q.waiters)} already queued "
                    f"(admission.max_queue_depth={self.config.max_queue_depth})",
                )
            expected = self._expected_wait_s_locked(limit)
            if deadline is not None:
                remaining = deadline - self.clock()
                if remaining <= expected:
                    # the deadline cannot absorb the queue: shed NOW so
                    # the client retries elsewhere instead of timing out
                    # here (deadline-aware dispatch ordering's dual)
                    self._shed(
                        tenant, "deadline",
                        f"deadline headroom {max(remaining, 0.0) * 1000:.0f} ms "
                        f"< expected queue wait {expected * 1000:.0f} ms",
                    )
            max_wait_ms = float(self.config.max_queue_wait_ms)
            sort_deadline = deadline
            if sort_deadline is None and max_wait_ms > 0:
                # EDF key for a deadline-LESS statement: its wait-time shed
                # bound — it must run by then or shed anyway.  Sorting it
                # at +inf instead starved writes behind any continuous
                # stream of deadlined queries (observed in the mixed
                # harness: 1 ingest batch in 10 s).
                sort_deadline = t_enter + max_wait_ms / 1000.0
            waiter = _Waiter(
                priority=priority, deadline=sort_deadline, seq=next(self._seq)
            )
            q.waiters.append(waiter)
            metrics.ADMISSION_QUEUE_DEPTH.set(len(q.waiters), tenant=tenant)
        # ---- wait outside the lock (bounded, deadline-clipped) -------------
        budget = max_wait_ms / 1000.0 if max_wait_ms > 0 else float("inf")
        if deadline is not None:
            budget = min(budget, max(deadline - self.clock(), 0.0))
        wait_until = self.clock() + budget
        while not waiter.event.is_set():
            timeout = wait_until - self.clock()
            if timeout > 0:
                if waiter.event.wait(
                    timeout=None if timeout == float("inf") else timeout
                ):
                    break
                continue  # spurious early return: re-check the budget
            with self._lock:
                if waiter.admitted:
                    break  # dispatched in the race window: keep the slot
                tq = self._tenants.get(tenant)
                if tq is not None and waiter in tq.waiters:
                    tq.waiters.remove(waiter)
                    metrics.ADMISSION_QUEUE_DEPTH.set(
                        len(tq.waiters), tenant=tenant
                    )
            reason = (
                "deadline"
                if deadline is not None and deadline - self.clock() <= 0
                else "wait_timeout"
            )
            self._shed(
                tenant, reason,
                f"queued {(self.clock() - t_enter) * 1000:.0f} ms "
                f"without a slot (limit {self._limit()})",
            )
        t_granted = self.clock()
        metrics.ADMISSION_WAIT_MS.observe((t_granted - t_enter) * 1000.0)
        metrics.ADMISSION_ADMITTED_TOTAL.inc()
        return t_granted

    def _release(self, t_granted: float):
        elapsed = max(self.clock() - t_granted, 0.0)
        with self._lock:
            self._running = max(self._running - 1, 0)
            metrics.ADMISSION_RUNNING.set(self._running)
            # recent behavior dominates the EWMA so the expected-wait
            # estimate tracks load shifts inside seconds
            self._service_s = ewma_update(self._service_s, elapsed)
            self._dispatch_locked()
