"""Anonymous telemetry reporter.

Role-equivalent of the reference's greptimedb-telemetry task
(reference common/greptimedb-telemetry/src/lib.rs: a background task that
reports version / mode / node count every N hours, disabled via
`enable_telemetry`): same scheduling and payload shape; the transport is a
local JSON sink because this environment has zero egress — swap `_emit`
for an HTTP POST where the reference uses reqwest.

Default OFF, like any respectable telemetry.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid


class TelemetryTask:
    def __init__(self, db, config):
        self.db = db
        self.config = config
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # stable anonymous installation id, persisted next to the catalog
        self._uuid_path = os.path.join(db.config.storage.data_home, ".telemetry_uuid")

    def start(self):
        if not self.config.enable:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True, name="telemetry")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # ---- internals --------------------------------------------------------
    def _install_id(self) -> str:
        try:
            with open(self._uuid_path) as f:
                return f.read().strip()
        except FileNotFoundError:
            uid = uuid.uuid4().hex
            with open(self._uuid_path, "w") as f:
                f.write(uid)
            return uid

    def build_report(self) -> dict:
        """The reference's payload shape (version/os/arch/mode/nodes)."""
        import platform

        n_tables = 0
        try:
            for database in self.db.catalog.databases():
                n_tables += len(self.db.catalog.tables(database))
        except Exception:  # noqa: BLE001 — never let telemetry break serving
            pass
        return {
            "uuid": self._install_id(),
            "version": "0.2.0-tpu",
            "os": platform.system().lower(),
            "arch": platform.machine(),
            "mode": "standalone",
            "nodes": 1,
            "table_count": n_tables,
            "ts": int(time.time()),
        }

    def _emit(self, report: dict):
        path = self.config.sink_path or os.path.join(
            self.db.config.storage.data_home, "telemetry_report.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f)
        os.replace(tmp, path)

    def report_once(self):
        self._emit(self.build_report())

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.report_once()
            except Exception:  # noqa: BLE001
                pass
            self._stop.wait(self.config.interval_hours * 3600.0)
