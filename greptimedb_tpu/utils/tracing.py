"""Lightweight span tracing with W3C-style context propagation.

The reference propagates tracing context across RPC boundaries in request
headers (reference src/common/telemetry/src/tracing_context.rs) and
instruments hot entry points.  We provide the same surface: spans with
trace/span ids, a contextvar-based current span, `traceparent` encode/decode
for cross-process propagation, and an in-memory exporter.

The exporter is a RING buffer (drop-oldest): a process that traces faster
than its `SelfTraceWriter` drains keeps the NEWEST spans — the ones an
operator debugging a live incident actually wants — and counts what it
sheds in `greptime_trace_spans_dropped_total` instead of silently pinning
the oldest 4096 spans forever.

Tail sampling rides a per-trace `TraceCollector`: the root span of a
self-traced statement carries a collector, every descendant (including
spans created on worker threads with an explicit `parent=`) buffers into
it, and the root's finalizer decides keep-or-drop AFTER the outcome is
known — slow/erroring statements are force-kept, fast ones head-sample
(utils/self_trace.py owns the policy; this module only carries spans).
Spans with no collector in scope export straight to the ring, exactly the
pre-collector behavior.
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

# Span/trace ids need uniqueness, not unpredictability: a process-local
# PRNG (seeded from the OS once) is ~50x cheaper than secrets.token_hex's
# per-call urandom read on this hot path.
_ids = random.Random()
_ids_lock = threading.Lock()


def _new_id(nbytes: int) -> str:
    with _ids_lock:
        return f"{_ids.getrandbits(nbytes * 8):0{nbytes * 2}x}"

# Span stage names observed in this process (the CI taxonomy gate in
# tests/conftest.py checks dotted names against the README contract so
# instrumentation cannot silently drift from the documented taxonomy).
SEEN_SPAN_NAMES: set[str] = set()

_HEX = set("0123456789abcdef")


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float = field(default_factory=time.time)
    end: float | None = None
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)
    status: str = ""  # "" (unset) | "OK" | "ERROR"
    status_message: str = ""
    service: str = ""
    collector: object | None = field(default=None, repr=False)

    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def add_event(self, name: str, **attrs):
        self.events.append({"name": name, "ts": time.time(), "attrs": attrs})

    def record_exception(self, exc: BaseException):
        """Mark this span failed with the exception as status + event
        (reference tracing_context records errors the same way): a span
        that unwinds through a raise must not look like a success."""
        self.status = "ERROR"
        self.status_message = f"{type(exc).__name__}: {exc}"
        self.add_event(
            "exception",
            type=type(exc).__name__,
            message=str(exc),
        )


class SpanExporter:
    """In-memory ring-buffer exporter; `SelfTraceWriter` drains it into the
    database's own trace table when self-tracing is on."""

    # drops accumulate locally and publish to the metric in batches of
    # this size (plus a flush at every drain) — per-drop Counter.inc on a
    # full ring measurably taxed the span hot path
    _PUBLISH_EVERY = 64

    def __init__(self, capacity: int = 4096):
        # deque(maxlen) evicts the oldest in O(1) — a full ring must stay
        # cheap, because with self-tracing off nothing ever drains it and
        # EVERY span pays the steady-state export cost
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._cap = capacity
        self._lock = threading.Lock()
        self.dropped = 0  # drops since the last drain
        self._unpublished = 0

    def _note_drop_locked(self) -> int:
        """Returns a batch of drops to publish outside the lock, or 0."""
        self.dropped += 1
        self._unpublished += 1
        if self._unpublished >= self._PUBLISH_EVERY:
            out, self._unpublished = self._unpublished, 0
            return out
        return 0

    def export(self, span: Span):
        publish = 0
        with self._lock:
            if len(self._spans) >= self._cap:
                publish = self._note_drop_locked()
            self._spans.append(span)
        if publish:
            _publish_drops(publish)

    def export_batch(self, spans: list[Span]):
        publish = 0
        with self._lock:
            for s in spans:
                if len(self._spans) >= self._cap:
                    publish += self._note_drop_locked()
                self._spans.append(s)
        if publish:
            _publish_drops(publish)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def drain(self) -> list[Span]:
        """Atomically take every buffered span (the writer's batch), and
        flush any unpublished drop count to the metric."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
            self.dropped = 0
            publish, self._unpublished = self._unpublished, 0
        if publish:
            _publish_drops(publish)
        return out

    def clear(self):
        with self._lock:
            self._spans.clear()


def _publish_drops(n: int):
    from . import metrics

    metrics.TRACE_SPANS_DROPPED.inc(n)


EXPORTER = SpanExporter()

# Open tail-sampling collectors by trace id: `extract_context` (the
# receiving side of an RPC) looks its caller's trace up here, so in
# one-process clusters the datanode-side spans JOIN the statement's
# collector and follow its keep/drop fate instead of bypassing tail
# sampling into the ring as root-less orphans.  Multi-process receivers
# miss the lookup and keep the export-direct behavior.
_collectors: dict[str, object] = {}
_collectors_lock = threading.Lock()


def register_collector(trace_id: str, collector):
    with _collectors_lock:
        _collectors[trace_id] = collector


def unregister_collector(trace_id: str):
    with _collectors_lock:
        _collectors.pop(trace_id, None)

_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar("span", default=None)
# Reentrancy guard: the SelfTraceWriter's own writes (and the metric
# self-scrape) run with tracing suppressed, so exporting traces can never
# generate new spans — no self-feeding loop, by construction.
_suppress: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "span_suppress", default=False
)
# Wire-protocol tag for root statement spans ("http" | "mysql" | "postgres"
# | ...): protocol servers set it around dispatch; the root span reads it.
_protocol: contextvars.ContextVar[str] = contextvars.ContextVar(
    "span_protocol", default=""
)
# Default service name for spans created without an explicit parent chain;
# roles override per-context (frontend statements, datanode RPC handlers).
_service: contextvars.ContextVar[str] = contextvars.ContextVar(
    "span_service", default="greptimedb_tpu.standalone"
)

_UNSET = object()


def current_span() -> Span | None:
    return _current.get()


def current_trace_id() -> str | None:
    s = _current.get()
    return s.trace_id if s is not None else None


def active_collector():
    s = _current.get()
    return s.collector if s is not None else None


def suppressed_active() -> bool:
    return _suppress.get()


@contextlib.contextmanager
def suppressed():
    """Scope in which `span()` is a no-op (nothing recorded anywhere)."""
    token = _suppress.set(True)
    try:
        yield
    finally:
        _suppress.reset(token)


@contextlib.contextmanager
def protocol_scope(name: str):
    """Tag statements dispatched under this scope with their wire protocol."""
    token = _protocol.set(name)
    try:
        yield
    finally:
        _protocol.reset(token)


def current_protocol() -> str:
    return _protocol.get()


@contextlib.contextmanager
def service_scope(name: str):
    """Default service.name for spans opened under this scope."""
    token = _service.set(name)
    try:
        yield
    finally:
        _service.reset(token)


class _NoopSpan(Span):
    """Returned under `suppressed()`: callers can set attributes/events
    freely, nothing is recorded."""


def _noop() -> _NoopSpan:
    return _NoopSpan(name="", trace_id="", span_id="", parent_id=None)


@contextlib.contextmanager
def span(name: str, parent=_UNSET, service: str | None = None, collector=_UNSET, **attributes):
    """One traced stage.

    `parent` defaults to the ambient contextvar span; pass it explicitly to
    parent a span created on a worker thread (thread pools do not inherit
    contextvars), which also carries the trace's collector across the hop.
    `collector`, when given, attaches a tail-sampling buffer at this span
    (the statement root); descendants inherit it through the parent chain.
    An exception unwinding through the span is recorded as status + event
    before re-raising.
    """
    if _suppress.get():
        yield _noop()
        return
    p = _current.get() if parent is _UNSET else parent
    inherited = p.collector if p is not None else None
    s = Span(
        name=name,
        trace_id=p.trace_id if p else _new_id(16),
        span_id=_new_id(8),
        parent_id=p.span_id if p else None,
        attributes=attributes,
        service=service or (p.service if p and p.service else _service.get()),
        collector=inherited if collector is _UNSET else collector,
    )
    SEEN_SPAN_NAMES.add(name)
    token = _current.set(s)
    try:
        yield s
    except BaseException as exc:
        s.record_exception(exc)
        raise
    finally:
        s.end = time.time()
        _current.reset(token)
        _record(s)


def _record(s: Span):
    if s.collector is not None:
        s.collector.add(s)
    else:
        EXPORTER.export(s)


def add_event(name: str, **attrs):
    """Attach an event to the current span, if any (retry attempts, hedge
    wins, breaker sheds, HBM degrade rounds — point-in-time facts that are
    not stages of their own)."""
    s = _current.get()
    if s is not None:
        s.add_event(name, **attrs)


def set_attribute(key: str, value):
    s = _current.get()
    if s is not None:
        s.attributes[key] = value


def inject_context() -> dict[str, str]:
    """Produce a `traceparent` header for the current span (W3C format)."""
    s = _current.get()
    if s is None or isinstance(s, _NoopSpan):
        return {}
    return {"traceparent": f"00-{s.trace_id}-{s.span_id}-01"}


def _parse_traceparent(tp: str) -> tuple[str, str] | None:
    """(trace_id, parent_span_id) from a traceparent header, or None when
    the header is malformed.  Per W3C: a version field that is not two hex
    chars, or the reserved 'ff', invalidates the header — previously only
    part LENGTHS were checked, so 'zz-<32 junk chars>-...' silently seeded
    a span with a garbage trace id."""
    parts = tp.split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or not set(version.lower()) <= _HEX:
        return None
    if version.lower() == "ff":
        return None  # reserved/invalid per the spec
    if len(trace_id) != 32 or not set(trace_id.lower()) <= _HEX:
        return None
    if len(span_id) != 16 or not set(span_id.lower()) <= _HEX:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


@contextlib.contextmanager
def extract_context(headers: dict[str, str], name: str = "remote", service: str | None = None, **attributes):
    """Continue a trace from a `traceparent` header on the receiving side.
    A missing or malformed header degrades to a fresh root span — the RPC
    is still traced, just not stitched into the caller's trace."""
    if _suppress.get():
        yield _noop()
        return
    parsed = _parse_traceparent(headers.get("traceparent", ""))
    if parsed is None:
        with span(name, service=service, **attributes) as s:
            yield s
        return
    trace_id, parent_span_id = parsed
    s = Span(
        name=name,
        trace_id=trace_id,
        span_id=_new_id(8),
        parent_id=parent_span_id,
        attributes=attributes,
        service=service or _service.get(),
        collector=_collectors.get(trace_id),
    )
    SEEN_SPAN_NAMES.add(name)
    token = _current.set(s)
    try:
        yield s
    except BaseException as exc:
        s.record_exception(exc)
        raise
    finally:
        s.end = time.time()
        _current.reset(token)
        _record(s)
