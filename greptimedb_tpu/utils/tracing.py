"""Lightweight span tracing with W3C-style context propagation.

The reference propagates tracing context across RPC boundaries in request
headers (reference src/common/telemetry/src/tracing_context.rs) and
instruments hot entry points.  We provide the same surface: spans with
trace/span ids, a contextvar-based current span, `traceparent` encode/decode
for cross-process propagation, and an in-memory exporter for tests.
"""

from __future__ import annotations

import contextlib
import contextvars
import secrets
import threading
import time
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float = field(default_factory=time.time)
    end: float | None = None
    attributes: dict = field(default_factory=dict)

    def duration(self) -> float:
        return (self.end or time.time()) - self.start


_current: contextvars.ContextVar[Span | None] = contextvars.ContextVar("span", default=None)


class SpanExporter:
    """In-memory exporter; swap for OTLP in production deployments."""

    def __init__(self, capacity: int = 4096):
        self._spans: list[Span] = []
        self._cap = capacity
        self._lock = threading.Lock()

    def export(self, span: Span):
        with self._lock:
            if len(self._spans) < self._cap:
                self._spans.append(span)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()


EXPORTER = SpanExporter()


def current_span() -> Span | None:
    return _current.get()


@contextlib.contextmanager
def span(name: str, **attributes):
    parent = _current.get()
    s = Span(
        name=name,
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
        parent_id=parent.span_id if parent else None,
        attributes=attributes,
    )
    token = _current.set(s)
    try:
        yield s
    finally:
        s.end = time.time()
        _current.reset(token)
        EXPORTER.export(s)


def inject_context() -> dict[str, str]:
    """Produce a `traceparent` header for the current span (W3C format)."""
    s = _current.get()
    if s is None:
        return {}
    return {"traceparent": f"00-{s.trace_id}-{s.span_id}-01"}


@contextlib.contextmanager
def extract_context(headers: dict[str, str], name: str = "remote"):
    """Continue a trace from a `traceparent` header on the receiving side."""
    tp = headers.get("traceparent", "")
    parts = tp.split("-")
    if len(parts) == 4 and len(parts[1]) == 32:
        s = Span(name=name, trace_id=parts[1], span_id=secrets.token_hex(8), parent_id=parts[2])
        token = _current.set(s)
        try:
            yield s
        finally:
            s.end = time.time()
            _current.reset(token)
            EXPORTER.export(s)
    else:
        with span(name) as s:
            yield s
