"""Plugins: typemap DI container + SQL interceptor hooks.

Role-equivalent of the reference's `Plugins` (reference common/base, a
type-keyed Send+Sync map threaded through every role builder) and the
frontend's `SqlQueryInterceptorRef` extension point (reference
frontend/src/instance.rs + plugins/src setup hooks, the surface enterprise
builds attach auth/audit/rewrites to).

Usage:
    plugins = Plugins()
    plugins.insert(MyInterceptor())           # keyed by its class
    db = Database(..., plugins=plugins)
    plugins.get(SqlQueryInterceptor)          # subclass-aware lookup
"""

from __future__ import annotations

import threading


class Plugins:
    """Type-keyed container; lookups match exact class or subclasses."""

    def __init__(self):
        self._items: dict[type, object] = {}
        self._lock = threading.Lock()

    def insert(self, obj: object, key: type | None = None):
        with self._lock:
            self._items[key or type(obj)] = obj

    def get(self, cls: type):
        """The registered instance of `cls` (or a subclass), or None."""
        with self._lock:
            hit = self._items.get(cls)
            if hit is not None:
                return hit
            for k, v in self._items.items():
                if issubclass(k, cls):
                    return v
        return None

    def get_all(self, cls: type) -> list:
        with self._lock:
            return [v for k, v in self._items.items() if issubclass(k, cls)]


class SqlQueryInterceptor:
    """Hook points around statement execution (reference
    SqlQueryInterceptorRef: pre_parsing / pre_execute / post_execute).
    Subclass and override; raise to reject, return to rewrite."""

    def pre_parsing(self, sql: str, ctx: dict) -> str:
        """Before the parser sees the text; return (possibly rewritten) SQL."""
        return sql

    def pre_execute(self, stmt, ctx: dict):
        """After parse, before execution; raise to reject the statement."""

    def post_execute(self, stmt, result, ctx: dict):
        """After execution; return the (possibly transformed) result."""
        return result
