"""Admission-style memory governance.

Role-equivalent of the reference's memory budgeting surfaces
(reference common/memory-manager/src/lib.rs policy/guard;
servers/src/request_memory_limiter.rs `max_in_flight_write_bytes`;
`max_concurrent_queries` in config/standalone.example.toml): bounded
in-flight write bytes with fail-fast rejection, and a concurrent-query
admission gate.  0 budget = unlimited (the reference's default)."""

from __future__ import annotations

import threading
from contextlib import contextmanager

from . import metrics
from .errors import RetryLaterError

WRITE_REJECTED = metrics.Counter(
    "memory_write_requests_rejected", "writes rejected by the in-flight byte budget"
)
QUERY_REJECTED = metrics.Counter(
    "memory_queries_rejected", "queries rejected by the concurrency gate"
)
SCAN_REJECTED = metrics.Counter(
    "memory_scans_rejected", "scan slices rejected by the scan-memory budget"
)


class ScanTracker:
    """Held scan-byte reservations for one query; release on close."""

    def __init__(self, gov: "MemoryGovernor"):
        self._gov = gov
        self._held = 0

    def add(self, nbytes: int):
        gov = self._gov
        if gov.max_scan_bytes <= 0:
            return
        with gov._lock:
            if gov._scan_bytes + nbytes > gov.max_scan_bytes:
                SCAN_REJECTED.inc()
                raise RetryLaterError(
                    f"scan memory budget exceeded ({gov._scan_bytes} + {nbytes}"
                    f" > {gov.max_scan_bytes}); narrow the query or retry later"
                )
            gov._scan_bytes += nbytes
            self._held += nbytes

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        if self._held:
            with self._gov._lock:
                self._gov._scan_bytes -= self._held
            self._held = 0


class MemoryGovernor:
    def __init__(
        self,
        max_in_flight_write_bytes: int = 0,
        max_concurrent_queries: int = 0,
        max_scan_bytes: int = 0,
    ):
        self.max_write_bytes = max_in_flight_write_bytes
        self.max_queries = max_concurrent_queries
        self.max_scan_bytes = max_scan_bytes
        self._lock = threading.Lock()
        self._in_flight_bytes = 0
        self._running_queries = 0
        self._scan_bytes = 0

    # ---- write admission ---------------------------------------------------
    @contextmanager
    def write_guard(self, nbytes: int):
        """Reserve `nbytes` of write budget for the duration; fail fast with
        RETRY_LATER when the budget is exhausted (the reference rejects with
        a retryable status rather than queueing)."""
        if self.max_write_bytes <= 0:
            yield
            return
        with self._lock:
            if self._in_flight_bytes + nbytes > self.max_write_bytes:
                WRITE_REJECTED.inc()
                raise RetryLaterError(
                    f"in-flight write bytes budget exceeded "
                    f"({self._in_flight_bytes} + {nbytes} > {self.max_write_bytes}); retry later"
                )
            self._in_flight_bytes += nbytes
        try:
            yield
        finally:
            with self._lock:
                self._in_flight_bytes -= nbytes

    # ---- query admission ---------------------------------------------------
    @contextmanager
    def query_guard(self):
        if self.max_queries <= 0:
            yield
            return
        with self._lock:
            if self._running_queries >= self.max_queries:
                QUERY_REJECTED.inc()
                raise RetryLaterError(
                    f"too many concurrent queries (limit {self.max_queries}); retry later"
                )
            self._running_queries += 1
        try:
            yield
        finally:
            with self._lock:
                self._running_queries -= 1

    # ---- scan admission ----------------------------------------------------
    @contextmanager
    def scan_guard(self, nbytes: int):
        """Account one scan slice against the scan-memory budget; raise
        RETRY_LATER when the budget would be exceeded (the reference's scan
        memory tiers; a huge SELECT degrades to retryable instead of OOM)."""
        if getattr(self, "max_scan_bytes", 0) <= 0:
            yield
            return
        with self._lock:
            if self._scan_bytes + nbytes > self.max_scan_bytes:
                SCAN_REJECTED.inc()
                raise RetryLaterError(
                    f"scan memory budget exceeded ({self._scan_bytes} + {nbytes}"
                    f" > {self.max_scan_bytes}); retry later or narrow the query"
                )
            self._scan_bytes += nbytes
        try:
            yield
        finally:
            with self._lock:
                self._scan_bytes -= nbytes

    def scan_tracker(self) -> "ScanTracker":
        """Cumulative scan-memory accounting for one query: `add` bytes as
        scan slices materialize; the query fails cleanly (RETRY_LATER) when
        it would exceed the budget instead of OOMing the process."""
        return ScanTracker(self)

    # ---- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "in_flight_write_bytes": self._in_flight_bytes,
                "max_in_flight_write_bytes": self.max_write_bytes,
                "running_queries": self._running_queries,
                "max_concurrent_queries": self.max_queries,
            }


def batch_nbytes(batch) -> int:
    """Approximate wire size of a RecordBatch (buffer byte sum)."""
    try:
        return batch.nbytes
    except Exception:  # noqa: BLE001
        return 0
