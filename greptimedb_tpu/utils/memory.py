"""Admission-style memory governance.

Role-equivalent of the reference's memory budgeting surfaces
(reference common/memory-manager/src/lib.rs policy/guard;
servers/src/request_memory_limiter.rs `max_in_flight_write_bytes`;
`max_concurrent_queries` in config/standalone.example.toml): bounded
in-flight write bytes with fail-fast rejection, and a concurrent-query
admission gate.  0 budget = unlimited (the reference's default)."""

from __future__ import annotations

import threading
from contextlib import contextmanager

from . import metrics
from .errors import RetryLaterError

WRITE_REJECTED = metrics.Counter(
    "memory_write_requests_rejected", "writes rejected by the in-flight byte budget"
)
QUERY_REJECTED = metrics.Counter(
    "memory_queries_rejected", "queries rejected by the concurrency gate"
)


class MemoryGovernor:
    def __init__(self, max_in_flight_write_bytes: int = 0, max_concurrent_queries: int = 0):
        self.max_write_bytes = max_in_flight_write_bytes
        self.max_queries = max_concurrent_queries
        self._lock = threading.Lock()
        self._in_flight_bytes = 0
        self._running_queries = 0

    # ---- write admission ---------------------------------------------------
    @contextmanager
    def write_guard(self, nbytes: int):
        """Reserve `nbytes` of write budget for the duration; fail fast with
        RETRY_LATER when the budget is exhausted (the reference rejects with
        a retryable status rather than queueing)."""
        if self.max_write_bytes <= 0:
            yield
            return
        with self._lock:
            if self._in_flight_bytes + nbytes > self.max_write_bytes:
                WRITE_REJECTED.inc()
                raise RetryLaterError(
                    f"in-flight write bytes budget exceeded "
                    f"({self._in_flight_bytes} + {nbytes} > {self.max_write_bytes}); retry later"
                )
            self._in_flight_bytes += nbytes
        try:
            yield
        finally:
            with self._lock:
                self._in_flight_bytes -= nbytes

    # ---- query admission ---------------------------------------------------
    @contextmanager
    def query_guard(self):
        if self.max_queries <= 0:
            yield
            return
        with self._lock:
            if self._running_queries >= self.max_queries:
                QUERY_REJECTED.inc()
                raise RetryLaterError(
                    f"too many concurrent queries (limit {self.max_queries}); retry later"
                )
            self._running_queries += 1
        try:
            yield
        finally:
            with self._lock:
                self._running_queries -= 1

    # ---- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "in_flight_write_bytes": self._in_flight_bytes,
                "max_in_flight_write_bytes": self.max_write_bytes,
                "running_queries": self._running_queries,
                "max_concurrent_queries": self.max_queries,
            }


def batch_nbytes(batch) -> int:
    """Approximate wire size of a RecordBatch (buffer byte sum)."""
    try:
        return batch.nbytes
    except Exception:  # noqa: BLE001
        return 0
