"""Admission-style memory governance.

Role-equivalent of the reference's memory budgeting surfaces
(reference common/memory-manager/src/lib.rs policy/guard;
servers/src/request_memory_limiter.rs `max_in_flight_write_bytes`;
`max_concurrent_queries` in config/standalone.example.toml): bounded
in-flight write bytes with fail-fast rejection, and a concurrent-query
admission gate.  0 budget = unlimited (the reference's default)."""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager

from . import metrics
from .deadline import current_deadline
from .errors import RetryLaterError

# ---- shared expected-wait estimator ---------------------------------------
# One rule set for BOTH admission layers (AdmissionController's tenant
# queues and MemoryGovernor's concurrency gate) so tuning and the
# measurement contract can never drift between the two copies:
#   - service time is an EWMA seeded small (a cold gate never sheds its
#     first burst) and updated ONLY with time measured from slot GRANT to
#     release — folding queue wait in would inflate the estimate under
#     congestion into a shed feedback loop;
#   - a new arrival's expected wait is a deliberate LOWER bound: everyone
#     ahead of it (plus itself) drains at `limit` statements per service
#     time.
SERVICE_EWMA_SEED_S = 0.05
SERVICE_EWMA_ALPHA = 0.2


def ewma_update(service_s: float, elapsed_s: float) -> float:
    return service_s + SERVICE_EWMA_ALPHA * (max(elapsed_s, 0.0) - service_s)


def expected_wait_s(service_s: float, ahead: int, limit: int) -> float:
    return service_s * float(ahead + 1) / float(max(limit, 1))

WRITE_REJECTED = metrics.Counter(
    "memory_write_requests_rejected", "writes rejected by the in-flight byte budget"
)
QUERY_REJECTED = metrics.Counter(
    "memory_queries_rejected", "queries rejected by the concurrency gate"
)
SCAN_REJECTED = metrics.Counter(
    "memory_scans_rejected", "scan slices rejected by the scan-memory budget"
)


class ScanTracker:
    """Held scan-byte reservations for one query; release on close."""

    def __init__(self, gov: "MemoryGovernor"):
        self._gov = gov
        self._held = 0

    def add(self, nbytes: int):
        gov = self._gov
        if gov.max_scan_bytes <= 0:
            return
        with gov._lock:
            if gov._scan_bytes + nbytes > gov.max_scan_bytes:
                SCAN_REJECTED.inc()
                raise RetryLaterError(
                    f"scan memory budget exceeded ({gov._scan_bytes} + {nbytes}"
                    f" > {gov.max_scan_bytes}); narrow the query or retry later"
                )
            gov._scan_bytes += nbytes
            self._held += nbytes

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        if self._held:
            with self._gov._lock:
                self._gov._scan_bytes -= self._held
            self._held = 0


class MemoryGovernor:
    def __init__(
        self,
        max_in_flight_write_bytes: int = 0,
        max_concurrent_queries: int = 0,
        max_scan_bytes: int = 0,
        gate_wait_s: float = 5.0,
    ):
        self.max_write_bytes = max_in_flight_write_bytes
        self.max_queries = max_concurrent_queries
        self.max_scan_bytes = max_scan_bytes
        # Longest an UNdeadlined statement blocks for a concurrency slot
        # before degrading to RETRY_LATER; deadlined statements clip to
        # their own remaining budget instead.
        self.gate_wait_s = gate_wait_s
        self._lock = threading.Lock()
        self._gate = threading.Condition(self._lock)
        self._in_flight_bytes = 0
        self._running_queries = 0
        self._scan_bytes = 0
        # EWMA of recent query service times: the expected-queue-wait
        # estimate deciding fail-fast vs block (shared rule set — see
        # module-level estimator above)
        self._service_s = SERVICE_EWMA_SEED_S
        # FIFO of waiter tokens: slots freed by releases hand off to the
        # HEAD, and fresh arrivals queue behind existing waiters — without
        # this, sustained arrivals barge past notified waiters every time
        # a slot turns over and a queued statement starves to its shed
        # bound despite continuous capacity churn
        self._gate_queue: deque = deque()

    # ---- write admission ---------------------------------------------------
    @contextmanager
    def write_guard(self, nbytes: int):
        """Reserve `nbytes` of write budget for the duration; fail fast with
        RETRY_LATER when the budget is exhausted (the reference rejects with
        a retryable status rather than queueing)."""
        if self.max_write_bytes <= 0:
            yield
            return
        with self._lock:
            if self._in_flight_bytes + nbytes > self.max_write_bytes:
                WRITE_REJECTED.inc()
                raise RetryLaterError(
                    f"in-flight write bytes budget exceeded "
                    f"({self._in_flight_bytes} + {nbytes} > {self.max_write_bytes}); retry later"
                )
            self._in_flight_bytes += nbytes
        try:
            yield
        finally:
            with self._lock:
                self._in_flight_bytes -= nbytes

    # ---- query admission ---------------------------------------------------
    @contextmanager
    def query_guard(self):
        """Concurrency gate with a bounded, deadline-clipped wait.

        The round-1 gate rejected the instant the limit was reached —
        even a statement with 10 s of deadline headroom got RETRY_LATER
        while a slot would have freed in 50 ms.  Now the gate fails fast
        ONLY when the statement's deadline cannot absorb the expected
        queue wait (EWMA service time x waiters ahead); otherwise it
        blocks until a slot frees, bounded by min(remaining deadline,
        gate_wait_s), and degrades to RETRY_LATER only when that bound
        expires with the gate still full."""
        if self.max_queries <= 0:
            yield
            return
        t0 = time.monotonic()
        deadline = current_deadline()
        with self._gate:
            # queue behind EXISTING waiters even when capacity is free:
            # admitting fresh arrivals ahead of the FIFO would starve a
            # notified waiter every time a slot turns over
            if self._running_queries >= self.max_queries or self._gate_queue:
                expected = expected_wait_s(
                    self._service_s, len(self._gate_queue), self.max_queries
                )
                budget = self.gate_wait_s
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= expected:
                        QUERY_REJECTED.inc()
                        raise RetryLaterError(
                            f"too many concurrent queries (limit "
                            f"{self.max_queries}) and deadline headroom "
                            f"{max(remaining, 0.0) * 1000:.0f} ms cannot absorb "
                            f"the expected {expected * 1000:.0f} ms queue wait; "
                            "retry later"
                        )
                    budget = min(budget, remaining)
                wait_until = time.monotonic() + budget
                token = object()
                self._gate_queue.append(token)
                try:
                    while (
                        self._running_queries >= self.max_queries
                        or self._gate_queue[0] is not token
                    ):
                        timeout = wait_until - time.monotonic()
                        if timeout <= 0:
                            QUERY_REJECTED.inc()
                            raise RetryLaterError(
                                f"too many concurrent queries (limit "
                                f"{self.max_queries}) after blocking "
                                f"{(time.monotonic() - t0) * 1000:.0f} ms; "
                                "retry later"
                            )
                        self._gate.wait(timeout=timeout)
                    self._gate_queue.popleft()  # our token: slot is ours
                finally:
                    try:
                        self._gate_queue.remove(token)  # shed path only
                    except ValueError:
                        pass
                    # a granted or shed HEAD changes who queue[0] is —
                    # wake everyone so the new head re-evaluates (notify()
                    # could wake a non-head that just re-sleeps)
                    self._gate.notify_all()
                metrics.GOVERNOR_GATE_WAIT_MS.observe(
                    (time.monotonic() - t0) * 1000.0
                )
            self._running_queries += 1
        # service time is measured from the GRANT: folding gate wait into
        # the EWMA would drag the estimate toward gate_wait_s under
        # congestion and re-create the instant-reject behavior this gate
        # exists to eliminate
        t_granted = time.monotonic()
        try:
            yield
        finally:
            elapsed = max(time.monotonic() - t_granted, 0.0)
            with self._gate:
                self._running_queries -= 1
                self._service_s = ewma_update(self._service_s, elapsed)
                # notify_all: notify() could hand the wakeup to a waiter
                # that is not the FIFO head, which re-sleeps — and the
                # head never hears about the freed slot
                self._gate.notify_all()

    # ---- scan admission ----------------------------------------------------
    @contextmanager
    def scan_guard(self, nbytes: int):
        """Account one scan slice against the scan-memory budget; raise
        RETRY_LATER when the budget would be exceeded (the reference's scan
        memory tiers; a huge SELECT degrades to retryable instead of OOM)."""
        if getattr(self, "max_scan_bytes", 0) <= 0:
            yield
            return
        with self._lock:
            if self._scan_bytes + nbytes > self.max_scan_bytes:
                SCAN_REJECTED.inc()
                raise RetryLaterError(
                    f"scan memory budget exceeded ({self._scan_bytes} + {nbytes}"
                    f" > {self.max_scan_bytes}); retry later or narrow the query"
                )
            self._scan_bytes += nbytes
        try:
            yield
        finally:
            with self._lock:
                self._scan_bytes -= nbytes

    def scan_tracker(self) -> "ScanTracker":
        """Cumulative scan-memory accounting for one query: `add` bytes as
        scan slices materialize; the query fails cleanly (RETRY_LATER) when
        it would exceed the budget instead of OOMing the process."""
        return ScanTracker(self)

    # ---- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "in_flight_write_bytes": self._in_flight_bytes,
                "max_in_flight_write_bytes": self.max_write_bytes,
                "running_queries": self._running_queries,
                "max_concurrent_queries": self.max_queries,
            }


def batch_nbytes(batch) -> int:
    """Approximate wire size of a RecordBatch (buffer byte sum)."""
    try:
        return batch.nbytes
    except Exception:  # noqa: BLE001
        return 0
