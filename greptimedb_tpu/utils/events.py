"""Event recorder: system events into `greptime_private` tables.

Role-equivalent of the reference's `common/event-recorder` crate (reference
common/event-recorder/src/: a background recorder batching events into
`greptime_private` system tables) and the slow-query pipeline
(`SlowQueryTimer` wrapped around frontend queries,
frontend/src/instance.rs:196-219, recorded into
greptime_private.slow_queries).

Events are enqueued non-blocking from the hot path; a daemon thread
drains the queue and writes rows through the normal ingest path, so the
tables are queryable with plain SQL:

    SELECT * FROM greptime_private.slow_queries
    SELECT * FROM greptime_private.events
"""

from __future__ import annotations

import json
import queue
import threading
import time

import numpy as np
import pyarrow as pa

EVENTS_DATABASE = "greptime_private"
SLOW_QUERY_TABLE = "slow_queries"
EVENTS_TABLE = "events"

# `seq` is a per-recorder unique tag: the storage engine dedups on
# (tags, ts) last-write-wins, so without it two events in the same
# millisecond would silently collapse to one.
_SLOW_QUERY_DDL = (
    f"CREATE TABLE IF NOT EXISTS {EVENTS_DATABASE}.{SLOW_QUERY_TABLE} ("
    "  seq STRING,"
    "  cost_time_ms BIGINT,"
    "  threshold_ms BIGINT,"
    "  query STRING,"
    "  is_promql BOOLEAN,"
    "  query_database STRING,"
    "  trace_id STRING,"
    "  fingerprint STRING,"
    "  span_tree STRING,"
    "  ts TIMESTAMP(3),"
    "  TIME INDEX (ts),"
    "  PRIMARY KEY (seq)"
    ")"
)

_EVENTS_DDL = (
    f"CREATE TABLE IF NOT EXISTS {EVENTS_DATABASE}.{EVENTS_TABLE} ("
    "  seq STRING,"
    "  event_type STRING,"
    "  payload STRING,"
    "  ts TIMESTAMP(3),"
    "  TIME INDEX (ts),"
    "  PRIMARY KEY (event_type, seq)"
    ")"
)


class EventRecorder:
    """Background writer of system events (daemon thread + queue)."""

    def __init__(self, db, flush_interval_s: float = 0.05, max_queue: int = 4096):
        import os
        import uuid

        self.db = db
        self.flush_interval_s = flush_interval_s
        self._queue: queue.Queue = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._ready = False
        self._seq_prefix = f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self._seq = 0
        # flush() synchronization: enqueued vs durably-handled counters
        self._sync = threading.Condition()
        self._enqueued = 0
        self._handled = 0
        self._thread = threading.Thread(target=self._run, daemon=True, name="event-recorder")
        self._thread.start()

    # ---- producers (non-blocking, drop on overflow) ------------------------
    def record_slow_query(
        self,
        query: str,
        cost_time_ms: int,
        threshold_ms: int,
        database: str,
        is_promql: bool = False,
        trace_id: str = "",
        fingerprint: str = "",
        span_tree: str = "",
    ):
        """`trace_id`/`fingerprint`/`span_tree` are filled by the
        self-observability loop (utils/self_trace.py) when a traced
        statement is force-kept: a user-reported slow query is then one
        Jaeger lookup away from its full span tree."""
        self._offer(
            (
                SLOW_QUERY_TABLE,
                {
                    "cost_time_ms": cost_time_ms,
                    "threshold_ms": threshold_ms,
                    "query": query,
                    "is_promql": is_promql,
                    "query_database": database,
                    "trace_id": trace_id,
                    "fingerprint": fingerprint,
                    "span_tree": span_tree,
                    "ts": int(time.time() * 1000),
                },
            )
        )

    def record_event(self, event_type: str, payload: dict):
        self._offer(
            (
                EVENTS_TABLE,
                {
                    "event_type": event_type,
                    "payload": json.dumps(payload),
                    "ts": int(time.time() * 1000),
                },
            )
        )

    def _offer(self, item):
        table, row = item
        with self._sync:
            self._seq += 1
            row = {"seq": f"{self._seq_prefix}-{self._seq}", **row}
        try:
            self._queue.put_nowait((table, row))
            with self._sync:
                self._enqueued += 1
        except queue.Full:
            pass  # shed events rather than block the query path

    # ---- consumer ----------------------------------------------------------
    def _ensure_tables(self):
        if self._ready:
            return
        # database-qualified DDL: this runs on the recorder THREAD, so it
        # must never touch db.current_database — flipping shared session
        # state from a background thread made concurrent foreground queries
        # resolve tables in greptime_private (observed: a UNION branch scan
        # returning slow_queries rows under load)
        if EVENTS_DATABASE not in self.db.catalog.databases():
            self.db.catalog.create_database(EVENTS_DATABASE, if_not_exists=True)
        self.db.sql(_SLOW_QUERY_DDL)
        self.db.sql(_EVENTS_DDL)
        self._migrate_slow_queries()
        self._ready = True

    def _migrate_slow_queries(self):
        """A pre-existing data dir created before the self-observability
        loop holds a slow_queries table WITHOUT the trace columns, and
        CREATE IF NOT EXISTS keeps that old schema — _conform_batch would
        then silently drop trace_id/fingerprint/span_tree from every row.
        Widen in place (regions first, catalog second — the ALTER
        ordering rule), programmatically because ALTER TABLE does not
        take db-qualified names and this thread must not flip the shared
        current_database."""
        from ..datatypes.data_type import ConcreteDataType
        from ..datatypes.schema import ColumnSchema, SemanticType

        try:
            meta = self.db.catalog.table(SLOW_QUERY_TABLE, EVENTS_DATABASE)
            missing = [
                c
                for c in ("trace_id", "fingerprint", "span_tree")
                if not meta.schema.has_column(c)
            ]
            if not missing:
                return
            with self.db.ddl_lock:
                meta = self.db.catalog.table(SLOW_QUERY_TABLE, EVENTS_DATABASE)
                schema = meta.schema
                for name in missing:
                    if schema.has_column(name):
                        continue
                    schema = schema.add_column(
                        ColumnSchema(
                            name=name,
                            data_type=ConcreteDataType.STRING,
                            semantic_type=SemanticType.FIELD,
                            nullable=True,
                        )
                    )
                for rid in meta.region_ids:
                    self.db.storage.region(rid).alter_schema(schema)
                meta.schema = schema
                self.db.catalog.update_table(meta)
        except Exception:  # noqa: BLE001 — the recorder must never kill the server
            import logging

            logging.getLogger("greptimedb_tpu.events").warning(
                "slow_queries trace-column migration failed", exc_info=True
            )

    def _run(self):
        pending: dict[str, list[dict]] = {}
        n_pending = 0
        last_flush = time.time()
        while not self._stop.is_set() or not self._queue.empty() or pending:
            try:
                table, row = self._queue.get(timeout=self.flush_interval_s)
                pending.setdefault(table, []).append(row)
                n_pending += 1
            except queue.Empty:
                pass
            now = time.time()
            if pending and (now - last_flush >= self.flush_interval_s or self._stop.is_set()):
                self._flush(pending)
                with self._sync:
                    self._handled += n_pending
                    self._sync.notify_all()
                pending = {}
                n_pending = 0
                last_flush = now

    def _flush(self, pending: dict[str, list[dict]]):
        try:
            self._ensure_tables()
            for table, rows in pending.items():
                cols: dict[str, list] = {}
                for row in rows:
                    for k, v in row.items():
                        cols.setdefault(k, []).append(v)
                arrays = {}
                for k, vals in cols.items():
                    if k == "ts":
                        arrays[k] = pa.array(np.asarray(vals, dtype=np.int64), pa.timestamp("ms"))
                    else:
                        arrays[k] = pa.array(vals)
                # system=True: the audit log must not be starved by the very
                # write-pressure incidents it exists to record (the user
                # write budget does not apply to internal system writes)
                self.db.insert_rows(
                    table, pa.record_batch(arrays), database=EVENTS_DATABASE, system=True
                )
        except Exception:  # noqa: BLE001 — the recorder must never kill the server
            import logging

            logging.getLogger("greptimedb_tpu.events").warning(
                "event recorder flush failed", exc_info=True
            )

    def flush(self, timeout_s: float = 5.0):
        """Wait until every event enqueued BEFORE this call has been handed
        to storage (or dropped after a logged failure)."""
        with self._sync:
            target = self._enqueued
            self._sync.wait_for(lambda: self._handled >= target, timeout=timeout_s)

    def stop(self):
        self.flush()
        self._stop.set()
        self._thread.join(timeout=5.0)


class SlowQueryTimer:
    """Context manager timing one query (reference SlowQueryTimer)."""

    def __init__(self, recorder: EventRecorder | None, cfg, query: str, database: str, is_promql=False):
        self.recorder = recorder
        self.cfg = cfg
        self.query = query
        self.database = database
        self.is_promql = is_promql
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.recorder is None or not self.cfg.enable:
            return False
        from . import tracing

        if tracing.active_collector() is not None:
            # a self-traced statement's slow row is written by the trace
            # finalizer (utils/self_trace.py) WITH its span tree attached;
            # writing here too would duplicate the row
            return False
        elapsed_ms = int((time.perf_counter() - self._t0) * 1000)
        if elapsed_ms < self.cfg.threshold_ms:
            return False
        import random

        if self.cfg.sample_ratio < 1.0 and random.random() > self.cfg.sample_ratio:
            return False
        self.recorder.record_slow_query(
            self.query, elapsed_ms, self.cfg.threshold_ms, self.database, self.is_promql
        )
        return False
