"""Synthetic device-tunnel RTT injection for offline benchmarking.

The production deployment reaches its accelerators over a tunnel whose
round-trip time (~103 ms observed) dwarfs warm compute: every dispatch
submission and every `device_get` pays the link, so the per-member
dispatch loop — not the chip — sets the dashboard-fleet QPS ceiling.
Local fakes hide that entirely.  `bench.py --rtt-ms N` (env
`GRAFT_BENCH_RTT_MS`) configures this module to sleep out a symmetric
half-RTT on each side of every device boundary crossing, making the
tunnel knee — and the mega-fusion win of ONE invocation per batch tick —
reproducible offline.

Off by default (`configure(0)` / unset env): `round_trip()` is a
zero-overhead no-op and the hot path is bit-for-bit today's.  Ghost
dispatches inside the fused cold build never pay the simulated link
(they never pay the real one either — the build pipelines uploads).
"""

from __future__ import annotations

import contextlib
import time

_RTT_S: float = 0.0


def configure(rtt_ms: float) -> None:
    """Set the simulated symmetric round-trip time in milliseconds
    (0 disables).  Process-global: the bench owns it, tests must reset."""
    global _RTT_S
    _RTT_S = max(float(rtt_ms), 0.0) / 1000.0


def rtt_ms() -> float:
    return _RTT_S * 1000.0


@contextlib.contextmanager
def round_trip(enabled: bool = True):
    """Sleep half the configured RTT before and after the wrapped device
    boundary crossing (submit or fetch) — the symmetric tunnel model."""
    half = _RTT_S / 2.0 if enabled else 0.0
    if half > 0.0:
        time.sleep(half)
    try:
        yield
    finally:
        if half > 0.0:
            time.sleep(half)
