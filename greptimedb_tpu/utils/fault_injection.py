"""Process-wide fault-injection registry for chaos testing.

The reference exercises its fault-tolerance machinery with black-box fuzz
targets that kill real processes (reference tests-fuzz/targets/failover);
that is slow and non-deterministic.  This registry gives the same coverage
in-process: hot paths call `fire("<point>")` at named injection points, and
a test arms a *fault plan* against a point — fail the next N calls with a
specific error class, inject latency, or run a callback (e.g. "complete the
failover now") at exactly that moment.

Named points wired into the codebase:

    flight.do_get      FlightDatanodeClient scan/partial_agg/execute_plan
    flight.do_put      FlightDatanodeClient.write
    flight.do_action   FlightDatanodeClient._action (open/close/flush/...)
    store.read         object-store reads (under RetryLayer, so injected
    store.write        faults exercise the retry path)
    wal.append         SharedLogStore.append
    meta.heartbeat     MetaClient.handle_heartbeat
    meta.get_route     MetaClient.get_route
    node.open_region   metasrv->datanode NodeManager gateway (procedure-side
    node.close_region  faults: open_candidate failing mid-failover, flushes
    node.flush_region  and downgrade fences failing mid-migration) — fired
    node.set_writable  by FaultInjectingNodeManager in distributed/metasrv.py
    flow.mirror        FlownodeClient.mirror_insert (frontend->flownode
                       mirrored inserts; best-effort by contract)
    flow.dedupe        FlownodeFlightServer.do_put AFTER a mirrored batch is
                       applied + registered in the dedupe window but BEFORE
                       the reply is written — an injected error here IS the
                       applied-but-reply-lost retry scenario exactly-once
                       dedupe exists for
    wal.prune_during_read  SharedLogStore._read_segment between frames, so a
                       test can run prune at the precise moment a reader
                       holds a sealed segment open
    replica.sync       Region.follower_sync entry (per sync round, before
                       the region lock) — wedge/fail the follower tailing
                       loop on cue
    admission.shed     AdmissionController.admit entry (utils/admission.py):
                       arming an error forces the next arrivals to shed
                       (counted under reason="injected"); a pure hook
                       observes every admission attempt
    hbm.exhausted      TileExecutor dispatch choke point, immediately
                       before each compiled tile program invocation —
                       arm with an error whose text contains
                       RESOURCE_EXHAUSTED to simulate device OOM and
                       drive the emergency-release + halve-chunk retry
                       loop without a real 16 GB working set
    dispatch.coalesce  TileExecutor waiter path, fired when a query
                       attaches to another query's in-flight device
                       dispatch (ctx: table) — observe/perturb coalition
                       formation at exactly the attach moment
    flow.diff_apply    dataflow task entry (flow/dataflow.py), fired per
                       mirrored diff batch BEFORE the operator graph folds
                       it — an injected error here exercises the
                       best-effort mirror contract (the user's insert must
                       survive, the flow records last_error)
    flow.join_dirty    dirty-window join marking (ctx: flow, side,
                       windows) — fired when a diff batch dirties output
                       windows, before the recompute runs
    flow.expire        flow EXPIRE AFTER dropping rows/states/index
                       windows (ctx: flow, expired count) — fired only
                       when something is actually expired
    index.segment_read segmented term-index segment fetch
                       (index/segmented.py, before the ranged read; ctx:
                       column, seg) — an injected error here must degrade
                       the lookup to a full-scan mask, never a wrong
                       result (TermIndexReader catches and returns None)
    index.build        SST index sidecar build entry (storage/sst.py
                       _build_indexes; ctx: file) — an injected error
                       yields an SST with NO sidecar (unpruned but
                       correct); the write itself must survive
    trace.self_write   SelfTraceWriter flush (utils/self_trace.py), fired
                       before each batch of spans is written into the own
                       trace table — an injected error here proves the
                       best-effort contract: the batch is dropped and
                       counted, the traced query is never failed or
                       slowed
    mesh.collective    multi-chip tile dispatch (parallel/tile_cache.py
                       mesh path), fired at the host-side choke point of
                       the shard_map merge — immediately before the
                       compiled collective program executes (ctx: table,
                       devices).  An injected error here proves the
                       degrade contract: the query falls back to the
                       single-chip dispatch path and still returns the
                       correct answer (greptime_tile_mesh_degraded_total)
    batch.pack         cross-query batcher pack point (parallel/
                       batcher.py), fired immediately before the batch's
                       deferred result buffers are flattened into the
                       single mega-readback (ctx: members, leaves).  An
                       injected error here proves the degrade contract:
                       every member falls back to its own solo dispatch
                       and still returns the bit-identical answer —
                       packing can delay a query, never corrupt one
    batch.fuse         mega-program fusion point (parallel/batcher.py),
                       fired before each member's dispatch capture
                       (ctx: op = "capture", table) and before the fused
                       single-invocation dispatch (ctx: op = "fuse",
                       members).  An injected capture error marks that
                       member unfusable (partial fusion: the rest still
                       fuse); an injected fuse error degrades the whole
                       tick to the per-member packed path — every member
                       still answers bit-identically, with no duplicated
                       side effects (greptime_batch_fuse_degraded_total)
    batch.result_cache windowed result cache probe/store (parallel/
                       batcher.py via the tile executor; ctx: op =
                       "get"/"put", table).  An injected error here is
                       swallowed: a failing cache lookup falls through
                       to a normal dispatch and a failing store keeps
                       the computed result — the cache is an
                       accelerator, never a correctness dependency
    balance.decide     elastic balancer decision enactment
                       (distributed/balancer.py), fired after hysteresis
                       admits a decision but BEFORE the procedure is
                       submitted (ctx: decision, table, region/node).  An
                       injected error here must leave routes and data
                       untouched — the decision is dropped, counted, and
                       re-proposed on a later tick
    repartition.copy   repartition data copy (distributed/repartition.py
                       _step_copy_data), fired per source region before
                       its rows are scanned into staging (ctx: table,
                       region).  A non-transient injected error rolls the
                       procedure back: staging is dropped, the write
                       fence pops, old routes stay authoritative
    migration.swap     region migration route swap (distributed/metasrv.py
                       update_metadata step), fired immediately before
                       the route flips to the candidate (ctx: region,
                       from/to node).  A non-transient injected error
                       rolls back: candidate closes, the old leader is
                       re-enabled, the route never moves
    wire.etcd          remote backend wire adapters (remote/wire.py
    wire.kafka         WireBackend.call), fired once per retry attempt
    wire.s3            BEFORE the socket work (ctx: backend, op, client,
                       endpoint) — protocol-level injection: arm a
                       TimeoutError to time a call out, a
                       RemoteProtocolError(retriable=True) to drive the
                       per-protocol retry classifier, or a match= filter
                       on `client` to partition one node's etcd client
                       while its rivals keep talking
    socket.connect     transport-level points inside remote/wire.py's
    socket.send        pooled Connection (ctx: backend, host, port; send/
    socket.recv        recv also pass conn + data/want) — arm
                       ConnectionResetError for a reset, TimeoutError for
                       a silent drop, latency_s for a slow link, or a
                       callback that conn.raw_send()s a prefix of
                       ctx["data"] then raises to put a torn frame on the
                       wire
    device.wedge       device health supervisor (utils/device_health.py),
                       fired INSIDE the per-device worker thread
                       immediately before every supervised device call
                       (ctx: kind = upload | dispatch | readback | mesh |
                       memory_stats | probe, device).  Arm a callback
                       that blocks on a test-controlled Event to wedge
                       the worker exactly like stuck native code: the
                       supervising thread abandons the call at its hard
                       deadline, quarantines the device, and the query
                       degrades down the existing ladder — zero failed
                       queries, the worker thread written off
    device.error       same spot, for the raised-error path: arm an
                       error to drive the breaker-style SUSPECT ->
                       QUARANTINED transition (error_threshold
                       consecutive raised device errors) without any
                       wedge

Production overhead is near zero: `fire()` is a module-level function whose
fast path is one read of a module global (`_ARMED`) — no locks, no dict
lookups — until a test arms a plan.  Plans are thread-safe; concurrent
callers decrement the same fail budget under the registry lock.

Usage (tests):

    from greptimedb_tpu.utils import fault_injection as fi

    plan = fi.REGISTRY.arm("flight.do_get", fail_times=2,
                           error=fl.FlightUnavailableError)
    ... run the query; first two region sub-queries raise, retries win ...
    assert plan.trips == 2
    fi.REGISTRY.disarm()

or scoped:

    with fi.REGISTRY.armed("store.write", fail_times=1, error=TimeoutError):
        engine.flush_region(rid)
"""

from __future__ import annotations

import contextlib
import threading
import time

POINTS = frozenset(
    {
        "flight.do_get",
        "flight.do_put",
        "flight.do_action",
        "store.read",
        "store.write",
        "wal.append",
        "meta.heartbeat",
        "meta.get_route",
        "node.open_region",
        "node.close_region",
        "node.flush_region",
        "node.set_writable",
        "flow.mirror",
        "flow.dedupe",
        "wal.prune_during_read",
        "replica.sync",
        "admission.shed",
        "hbm.exhausted",
        "dispatch.coalesce",
        "flow.diff_apply",
        "flow.join_dirty",
        "flow.expire",
        "index.segment_read",
        "index.build",
        "trace.self_write",
        "mesh.collective",
        "tile.fused_build",
        "tql.tile",
        "recorder.emit",
        "ingest.group_commit",
        "batch.pack",
        "batch.fuse",
        "batch.result_cache",
        "balance.decide",
        "repartition.copy",
        "migration.swap",
        # wire-level remote backends (remote/): per-attempt protocol
        # injection on each adapter, plus transport-level points inside
        # the pooled connection (resets, drops, latency; partial frames
        # via a plan callback that raw_send()s a prefix then raises)
        "wire.etcd",
        "wire.kafka",
        "wire.s3",
        "socket.connect",
        "socket.send",
        "socket.recv",
        # device health supervisor: in-worker wedge (never-returns via a
        # test-controlled Event) + raised-error storm
        "device.wedge",
        "device.error",
    }
)

# Module-level fast flag: fire() returns immediately while no plan is armed
# anywhere in the process.  Only the registry mutates it, under its lock.
_ARMED = False


class FaultPlan:
    """One armed fault at one point.

    Behaviour per matching hit, in order: first `skip` hits pass through,
    the next `fail_times` hits *trip* (sleep `latency_s`, run `callback`,
    raise `error` if set), every later hit passes through again — the
    "fail-N-then-succeed" shape retry tests need.  A plan with no error is
    a pure hook (latency and/or callback only).
    """

    def __init__(
        self,
        point: str,
        *,
        fail_times: int = 1,
        error: type[BaseException] | BaseException | None = None,
        latency_s: float = 0.0,
        skip: int = 0,
        match=None,
        callback=None,
    ):
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r}; known: {sorted(POINTS)}")
        self.point = point
        self.fail_times = fail_times
        self.error = error
        self.latency_s = latency_s
        self.skip = skip
        self.match = match
        self.callback = callback
        self.hits = 0  # matching calls observed (including pass-throughs)
        self.trips = 0  # calls that actually injected the fault

    def _make_error(self) -> BaseException | None:
        if self.error is None:
            return None
        if isinstance(self.error, BaseException):
            return self.error
        try:
            return self.error(f"injected fault at {self.point}")
        except TypeError:
            # some exception classes (pyarrow Flight) take no free-form args
            return self.error()


class FaultRegistry:
    """Thread-safe map of point -> armed plans (a test may stack several)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._plans: dict[str, list[FaultPlan]] = {}

    # ---- arming ------------------------------------------------------------
    def arm(self, point: str, **kwargs) -> FaultPlan:
        global _ARMED
        plan = FaultPlan(point, **kwargs)
        with self._lock:
            self._plans.setdefault(point, []).append(plan)
            _ARMED = True
        return plan

    def disarm(self, point: str | None = None):
        """Remove every plan at `point`, or every plan everywhere."""
        global _ARMED
        with self._lock:
            if point is None:
                self._plans.clear()
            else:
                self._plans.pop(point, None)
            _ARMED = bool(self._plans)

    def remove(self, plan: FaultPlan):
        """Remove one specific plan, leaving any stacked plans at the same
        point armed."""
        global _ARMED
        with self._lock:
            plans = self._plans.get(plan.point)
            if plans is not None and plan in plans:
                plans.remove(plan)
                if not plans:
                    self._plans.pop(plan.point, None)
            _ARMED = bool(self._plans)

    @contextlib.contextmanager
    def armed(self, point: str, **kwargs):
        plan = self.arm(point, **kwargs)
        try:
            yield plan
        finally:
            self.remove(plan)

    # ---- firing ------------------------------------------------------------
    def fire(self, point: str, **ctx):
        """Called from injection points.  Decides under the lock, acts
        (sleep/callback/raise) outside it so a latency fault never blocks
        other threads' fault decisions."""
        to_trip: FaultPlan | None = None
        with self._lock:
            for plan in self._plans.get(point, ()):
                if plan.match is not None and not plan.match(ctx):
                    continue
                plan.hits += 1
                if plan.hits <= plan.skip:
                    continue
                if plan.trips >= plan.fail_times:
                    continue
                plan.trips += 1
                to_trip = plan
                break
        if to_trip is None:
            return
        if to_trip.latency_s:
            time.sleep(to_trip.latency_s)
        if to_trip.callback is not None:
            to_trip.callback(ctx)
        err = to_trip._make_error()
        if err is not None:
            raise err


REGISTRY = FaultRegistry()


def fire(point: str, **ctx):
    """Hot-path hook: no-op unless some plan is armed process-wide."""
    if not _ARMED:
        return
    REGISTRY.fire(point, **ctx)
