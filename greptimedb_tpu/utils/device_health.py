"""Per-device health supervision: bounded device calls, wedge detection,
quarantine + heal.

The bench history proves the failure mode this module closes: a wedged
native XLA call holds the GIL-adjacent runtime hostage and no raised-error
ladder (HBM retry, CPU fallback) ever fires, because nothing is *raised* —
the call simply never returns.  BENCH r02–r05 published rc=124 for exactly
this reason, and PR 12 bolted a jax-free supervisor onto bench.py to
survive it.  This is the production twin: every blocking device
interaction on the query path (upload, compile+dispatch, readback,
memory_stats probe, mesh collective) runs through `supervised_call`,
which executes the call on a dedicated per-device worker thread under a
hard deadline:

    timeout = min(device.call_timeout_s, statement's remaining budget)

A call that neither returns nor raises by the deadline is **abandoned** —
the future is detached and the worker thread written off (the PR 2
`_fanout` abandonment pattern; a wedged native call cannot be cancelled,
only orphaned) — a fresh worker is spawned in its place
(`greptime_device_worker_refills_total` counts the bounded leak), the
device transitions to QUARANTINED, and the caller gets a
`DeviceWedgedError` it can degrade on immediately: the existing ladder
(host consolidation / cold-serve / scan path / CPU fallback) turns the
wedge into bounded added latency, never a failed query.

Per-device state machine:

    HEALTHY --raised device error--> SUSPECT
    SUSPECT --error_threshold consecutive errors--> QUARANTINED
    SUSPECT --success--> HEALTHY
    any     --abandoned (wedged) call--> QUARANTINED
    QUARANTINED --heal prober picks it up--> PROBING
    PROBING --probe_successes consecutive in-deadline ghost calls--> HEALTHY
    PROBING --probe failure/timeout--> QUARANTINED

Quarantine consequences are wired at the call sites: the tile cache drops
device planes (resident state is rebuildable cache, not truth — see
`TileCacheManager.health_sync`), chunk placement and the mesh path shrink
to the surviving device set, and the batcher's members degrade to solo
runs that land on healthy devices or the host path.

`device.supervised = false` restores direct in-thread calls bit-for-bit:
`supervised_call` then IS `fn()` — no worker hop, no timeout, no state.

Fault points (conftest coverage gate): `device.wedge` fires inside the
worker-run callable so a test-controlled callback that blocks on an Event
wedges the worker exactly like stuck native code (releases the GIL, so
the supervising thread still times out); `device.error` fires at the same
spot for raised-error storms that drive the breaker-style SUSPECT →
QUARANTINED path without any wedge.
"""

from __future__ import annotations

import contextlib
import logging
import queue
import threading
import time

from . import flight_recorder, metrics, tracing
from .deadline import check_deadline, current_deadline
from .errors import QueryTimeoutError
from .fault_injection import fire as _fault_fire

HEALTHY = "HEALTHY"
SUSPECT = "SUSPECT"
QUARANTINED = "QUARANTINED"
PROBING = "PROBING"

# gauge encoding for greptime_device_health_state (per device label)
_STATE_CODE = {HEALTHY: 0, SUSPECT: 1, QUARANTINED: 2, PROBING: 3}

_LOG = logging.getLogger("greptimedb_tpu.device_health")

# ---- ambient-scope propagation ---------------------------------------------
# Supervised callables run on a WORKER thread, but callers' thread-local
# execution scopes (tile_cache's flow-maintenance and fused-build depths)
# must hold inside them — metric attribution like
# greptime_flow_device_dispatch_total reads those flags at dispatch time.
# A module owning such a scope registers a (capture, apply) pair:
# capture() runs on the calling thread and returns a token, apply(token)
# is a context manager entered on the worker around the callable.
_PROPAGATORS: list = []


def register_scope_propagator(capture, apply) -> None:
    _PROPAGATORS.append((capture, apply))


# Bypass predicates: when any returns True on the CALLING thread, the
# supervisor runs the callable inline (unsupervised).  Background
# best-effort work (tile_cache's fused family builder) registers here:
# on a saturated box its ghost dispatches can genuinely outlast the
# foreground deadline, and abandoning one would quarantine devices — and
# drop every resident plane — over a stall no query is waiting on.  A
# wedge there hangs only the daemon builder thread (pre-supervisor
# behavior); the foreground path it primes stays fully supervised.
_BYPASS: list = []


def register_bypass(predicate) -> None:
    _BYPASS.append(predicate)


class DeviceWedgedError(RuntimeError):
    """A supervised device call was abandoned at its deadline (or failed
    fast because every target device is quarantined).  Deliberately NOT a
    QueryTimeoutError: the statement's own deadline still owns the query,
    and the engine's CPU-fallback ladder must catch this one."""


class DeviceCallError(RuntimeError):
    """Raised-error twin for the `device.error` fault point."""


class _Box:
    """One supervised call's detachable future."""

    __slots__ = ("event", "result", "exc")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.exc = None


class _Worker:
    """One device's dedicated call thread.  A wedged call never returns,
    so the thread is single-purpose and disposable: the supervisor writes
    it off (`dead = True`) and spawns a replacement; if the orphan ever
    wakes it notices and exits instead of racing its successor."""

    def __init__(self, name: str):
        self.dead = False
        self._q: queue.Queue = queue.Queue()
        self.thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self.thread.start()

    def submit(self, fn) -> _Box:
        box = _Box()
        self._q.put((fn, box))
        return box

    def stop(self):
        self.dead = True
        self._q.put(None)

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None or self.dead:
                return
            fn, box = item
            try:
                box.result = fn()
            except BaseException as e:  # noqa: BLE001 — ferried to the caller
                box.exc = e
            box.event.set()
            if self.dead:
                return


class _DeviceState:
    __slots__ = (
        "state", "consecutive_failures", "abandoned_calls", "quarantines",
        "heals", "probe_streak", "last_probe_ms", "quarantined_at",
        "last_error",
    )

    def __init__(self):
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.abandoned_calls = 0
        self.quarantines = 0
        self.heals = 0
        self.probe_streak = 0
        self.last_probe_ms = 0
        self.quarantined_at = None  # monotonic seconds, while quarantined
        self.last_error = ""


class DeviceSupervisor:
    """Process-wide device health authority (one per process, like the
    flight recorder): the most recently opened Database's `device.*`
    config governs it.  Unconfigured (or `supervised = false`) it is a
    strict no-op — `call()` runs the callable in-thread, bit-for-bit."""

    def __init__(self):
        self._lock = threading.RLock()
        self._cfg = None
        self._devices: list = []
        self._states: dict[int, _DeviceState] = {}
        self._workers: dict[int, _Worker] = {}
        self._worker_gen = 0
        self._abandoned: list[threading.Thread] = []
        # bumped on every quarantine AND heal: the tile cache compares it
        # to decide when to drop device planes / re-read placement
        self._generation = 0
        self._prober: threading.Thread | None = None
        self._prober_stop = threading.Event()

    # ---- configuration -----------------------------------------------------
    def configure(self, cfg, devices=None):
        """Wire the `device.*` config section (and the live device list)
        from Database startup.  Passing cfg=None leaves supervision off."""
        with self._lock:
            self._cfg = cfg
            if devices is not None:
                self._devices = list(devices)

    @property
    def enabled(self) -> bool:
        cfg = self._cfg
        return cfg is not None and bool(getattr(cfg, "supervised", False))

    @property
    def generation(self) -> int:
        return self._generation

    def _ensure_devices(self):
        if not self._devices:
            import jax

            self._devices = list(jax.devices())
        return self._devices

    # ---- state queries -----------------------------------------------------
    def _state(self, idx: int) -> _DeviceState:
        st = self._states.get(idx)
        if st is None:
            st = self._states[idx] = _DeviceState()
        return st

    def state_of(self, idx: int) -> str:
        with self._lock:
            st = self._states.get(idx)
            return st.state if st is not None else HEALTHY

    def healthy_indices(self, n: int) -> tuple[int, ...]:
        """Device indices usable for placement/dispatch (not quarantined
        and not mid-probe).  Unknown devices are healthy by default."""
        if not self.enabled:
            return tuple(range(n))
        with self._lock:
            return tuple(
                i for i in range(n)
                if self._states.get(i) is None
                or self._states[i].state not in (QUARANTINED, PROBING)
            )

    def all_quarantined(self, n: int) -> bool:
        return n > 0 and not self.healthy_indices(n)

    # ---- the supervised call -----------------------------------------------
    def call(self, kind: str, fn, devices=None, countable=None,
             _probe: bool = False):
        """Run `fn` on the target device's worker thread under the hard
        deadline.  `devices` names the involved device indices (None =
        unknown: the call is attributed to every known device — a wedge
        then quarantines them all and the heal prober re-admits the
        innocent ones).  `countable` filters which raised exceptions feed
        the error breaker (site-specific benign errors — mesh shape
        ineligibility, RESOURCE_EXHAUSTED owned by the HBM ladder — must
        not poison device health)."""
        if not self.enabled or any(p() for p in _BYPASS):
            return fn()
        cfg = self._cfg
        devs = self._ensure_devices()
        if devices is None:
            indices = tuple(range(len(devs))) or (0,)
        else:
            indices = tuple(devices) or (0,)
        if not _probe and all(
            self.state_of(i) in (QUARANTINED, PROBING) for i in indices
        ):
            raise DeviceWedgedError(
                f"device call {kind!r} refused: device(s) "
                f"{sorted(indices)} quarantined"
            )
        timeout = float(getattr(cfg, "call_timeout_s", 30.0) or 30.0)
        if not _probe:
            d = current_deadline()
            if d is not None:
                remaining = d - time.monotonic()
                if remaining <= 0:
                    check_deadline()
                timeout = min(timeout, remaining)
        timeout = max(timeout, 0.001)

        tokens = [(apply, capture()) for capture, apply in _PROPAGATORS]

        def job():
            with contextlib.ExitStack() as scopes:
                for apply, token in tokens:
                    scopes.enter_context(apply(token))
                _fault_fire("device.wedge", kind=kind, device=indices[0])
                _fault_fire("device.error", kind=kind, device=indices[0])
                return fn()

        worker = self._worker_for(indices[0])
        box = worker.submit(job)
        if not box.event.wait(timeout):
            self._abandon(worker, kind, indices, timeout)
            raise DeviceWedgedError(
                f"device call {kind!r} abandoned after {timeout:.3f}s "
                f"(device(s) {sorted(indices)} quarantined; worker thread "
                "written off)"
            )
        if box.exc is not None:
            if not isinstance(
                box.exc, (QueryTimeoutError, DeviceWedgedError)
            ) and "RESOURCE_EXHAUSTED" not in str(box.exc) and (
                countable is None or countable(box.exc)
            ):
                self._record_error(indices, box.exc)
            raise box.exc
        self._record_success(indices)
        return box.result

    def _worker_for(self, idx: int) -> _Worker:
        with self._lock:
            w = self._workers.get(idx)
            if w is None or w.dead:
                if w is not None:
                    # replacing a written-off worker: the bounded leak
                    metrics.DEVICE_WORKER_REFILLS.inc()
                self._worker_gen += 1
                w = self._workers[idx] = _Worker(
                    f"device-worker-{idx}-g{self._worker_gen}"
                )
            return w

    def _abandon(self, worker: _Worker, kind: str, indices, timeout: float):
        with self._lock:
            # written off but left in the slot: _worker_for sees the dead
            # entry on the next call and replaces it, counting the refill
            worker.dead = True
            self._abandoned.append(worker.thread)
        metrics.DEVICE_HEALTH_ABANDONED.inc(kind=kind)
        flight_recorder.flag("device_abandoned")
        _LOG.warning(
            "device call %r abandoned after %.3fs on device(s) %s; "
            "worker %s written off",
            kind, timeout, sorted(indices), worker.thread.name,
        )
        with self._lock:
            for i in indices:
                st = self._state(i)
                st.abandoned_calls += 1
                st.consecutive_failures += 1
                st.last_error = f"abandoned:{kind}"
                self._transition_locked(i, st, QUARANTINED)
        self._start_prober()

    # ---- error breaker -----------------------------------------------------
    def _record_error(self, indices, exc: BaseException):
        threshold = max(int(getattr(self._cfg, "error_threshold", 3) or 3), 1)
        with self._lock:
            for i in indices:
                st = self._state(i)
                if st.state in (QUARANTINED, PROBING):
                    continue  # only the heal prober moves these
                st.consecutive_failures += 1
                st.last_error = f"{type(exc).__name__}: {exc}"[:160]
                if st.consecutive_failures >= threshold:
                    self._transition_locked(i, st, QUARANTINED)
                elif st.state == HEALTHY:
                    self._transition_locked(i, st, SUSPECT)
        self._start_prober()

    def _record_success(self, indices):
        with self._lock:
            for i in indices:
                st = self._states.get(i)
                if st is None:
                    continue
                if st.state == SUSPECT:
                    self._transition_locked(i, st, HEALTHY)
                if st.state == HEALTHY:
                    st.consecutive_failures = 0

    # ---- transitions -------------------------------------------------------
    def _transition_locked(self, idx: int, st: _DeviceState, to: str):
        frm = st.state
        if frm == to:
            return
        st.state = to
        if to == QUARANTINED:
            if frm != PROBING:
                st.quarantines += 1
                self._generation += 1
                metrics.DEVICE_HEALTH_QUARANTINES.inc()
            if st.quarantined_at is None:
                st.quarantined_at = time.monotonic()
            st.probe_streak = 0
        elif to == HEALTHY and frm == PROBING:
            st.heals += 1
            st.consecutive_failures = 0
            st.probe_streak = 0
            st.quarantined_at = None
            self._generation += 1
            metrics.DEVICE_HEALTH_HEALS.inc()
        metrics.DEVICE_HEALTH_TRANSITIONS.inc(to=to)
        metrics.DEVICE_HEALTH_STATE.set(_STATE_CODE[to], device=str(idx))
        tracing.add_event(
            "device.health", device=idx, from_state=frm, to_state=to
        )
        flight_recorder.flag_next(f"device_{to.lower()}")
        _LOG.warning("device %d health: %s -> %s", idx, frm, to)

    # ---- heal prober -------------------------------------------------------
    def _start_prober(self):
        with self._lock:
            if self._prober is not None and self._prober.is_alive():
                return
            self._prober_stop = threading.Event()
            self._prober = threading.Thread(
                target=self._probe_loop, name="device-heal-prober", daemon=True
            )
            self._prober.start()

    def _probe_loop(self):
        stop = self._prober_stop
        interval = float(getattr(self._cfg, "probe_interval_s", 1.0) or 1.0)
        while not stop.wait(interval):
            with self._lock:
                pending = [
                    i for i, st in self._states.items()
                    if st.state in (QUARANTINED, PROBING)
                ]
            if not pending:
                return  # idle prober exits; next quarantine restarts it
            for i in pending:
                if stop.is_set():
                    return
                self._probe_one(i)

    def _probe_one(self, idx: int):
        cfg = self._cfg
        need = max(int(getattr(cfg, "probe_successes", 3) or 3), 1)
        with self._lock:
            st = self._states.get(idx)
            if st is None or st.state not in (QUARANTINED, PROBING):
                return
            self._transition_locked(idx, st, PROBING)

        def ghost():
            # a tiny real round-trip on the quarantined device: upload,
            # compute, fetch — the minimal proof the device answers again
            import jax
            import numpy as np

            dev = self._ensure_devices()[idx]
            x = jax.device_put(np.arange(8, dtype=np.float32), dev)
            return float(jax.device_get(x).sum())

        ok = False
        try:
            self.call("probe", ghost, devices=(idx,), _probe=True)
            ok = True
        except BaseException:  # noqa: BLE001 — a failing probe re-quarantines
            ok = False
        now_ms = int(time.time() * 1000)
        with self._lock:
            st = self._states.get(idx)
            if st is None:
                return
            st.last_probe_ms = now_ms
            metrics.DEVICE_HEALTH_PROBES.inc(result="ok" if ok else "fail")
            if st.state != PROBING:
                return
            if ok:
                st.probe_streak += 1
                if st.probe_streak >= need:
                    self._transition_locked(idx, st, HEALTHY)
            else:
                st.probe_streak = 0
                self._transition_locked(idx, st, QUARANTINED)

    # ---- introspection -----------------------------------------------------
    def health_rows(self, devices=None) -> list[dict]:
        """Per-device snapshot shared by information_schema.device_health,
        /debug/tile and the bench digest."""
        devs = list(devices) if devices is not None else list(self._devices)
        if not devs:
            devs = list(self._devices)
        now = time.monotonic()
        rows = []
        with self._lock:
            for i, dev in enumerate(devs):
                st = self._states.get(i)
                q_age = 0
                if st is not None and st.quarantined_at is not None:
                    q_age = int((now - st.quarantined_at) * 1000)
                rows.append({
                    "device": i,
                    "device_kind": str(dev),
                    "state": st.state if st is not None else HEALTHY,
                    "consecutive_failures": (
                        st.consecutive_failures if st is not None else 0
                    ),
                    "abandoned_calls": (
                        st.abandoned_calls if st is not None else 0
                    ),
                    "quarantines": st.quarantines if st is not None else 0,
                    "heals": st.heals if st is not None else 0,
                    "last_probe_ms": st.last_probe_ms if st is not None else 0,
                    "quarantine_age_ms": q_age,
                    "last_error": st.last_error if st is not None else "",
                })
        return rows

    def digest(self) -> dict:
        """Compact rollup for /debug/tile and the bench mixed record."""
        with self._lock:
            states: dict[str, int] = {}
            abandoned = quarantines = heals = failures = 0
            for st in self._states.values():
                states[st.state] = states.get(st.state, 0) + 1
                abandoned += st.abandoned_calls
                quarantines += st.quarantines
                heals += st.heals
                failures += st.consecutive_failures
            n_known = len(self._states)
        n_devices = len(self._devices)
        if n_devices > n_known:
            states[HEALTHY] = states.get(HEALTHY, 0) + (n_devices - n_known)
        return {
            "supervised": self.enabled,
            "states": states,
            "abandoned_calls": abandoned,
            "quarantines": quarantines,
            "heals": heals,
            "consecutive_failures": failures,
        }

    def abandoned_worker_threads(self) -> list[threading.Thread]:
        """Written-off worker threads (the conftest session-teardown gate
        asserts none outlive the suite except under `wedge`-marked tests,
        which hold the wedge Event and must release it at teardown)."""
        with self._lock:
            return list(self._abandoned)

    # ---- test / lifecycle hooks --------------------------------------------
    def reset(self):
        """Return every device to HEALTHY and drop per-device counters —
        test isolation (the supervisor is process-wide, the golden suite
        runs in the same process as the chaos tests).  Written-off worker
        threads stay recorded for the teardown gate; live workers are
        stopped so an idle process holds no supervision threads."""
        with self._lock:
            self._prober_stop.set()
            self._states.clear()
            for w in self._workers.values():
                w.stop()
            self._workers.clear()
        prober = self._prober
        if prober is not None and prober is not threading.current_thread():
            prober.join(timeout=5.0)
        with self._lock:
            self._prober = None


SUPERVISOR = DeviceSupervisor()


def supervised_call(kind: str, fn, devices=None, countable=None):
    """Module-level convenience: route one blocking device interaction
    through the process supervisor (a direct `fn()` when supervision is
    off — the off-safe bit-for-bit contract)."""
    return SUPERVISOR.call(kind, fn, devices=devices, countable=countable)
