"""Prometheus-style in-process metrics registry.

The reference exposes lazy_static prometheus counters/histograms per crate
(e.g. reference src/mito2/src/metrics.rs) served at /metrics.  We keep the
same shape: a process-global registry of counters, gauges and histograms,
renderable in the Prometheus text exposition format.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    def __init__(self, name: str, help_: str):
        self.name, self.help = name, help_
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += amount

    def get(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum across every label combination.  get() with no labels reads
        only the unlabeled key — which stays 0 forever on a counter whose
        inc() sites always attach labels — so aggregate readers (bench
        records, dashboards) must use this instead."""
        with self._lock:
            return sum(self._values.values())

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_fmt_labels(key)} {v}")
        return lines


class Gauge(Counter):
    def set(self, value: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for key, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_fmt_labels(key)} {v}")
        return lines


class Histogram:
    def __init__(self, name: str, help_: str, buckets=_DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            self._sums[key] += value
            self._totals[key] += 1

    class _Timer:
        def __init__(self, hist, labels):
            self._hist, self._labels = hist, labels

        def __enter__(self):
            self._start = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._hist.observe(time.perf_counter() - self._start, **self._labels)
            return False

    def time(self, **labels) -> "Histogram._Timer":
        return self._Timer(self, labels)

    def total(self, **labels) -> int:
        return self._totals.get(tuple(sorted(labels.items())), 0)

    def sum(self, **labels) -> float:
        return self._sums.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for key in sorted(self._counts):
            cum = 0
            for ub, c in zip(self.buckets, self._counts[key]):
                cum += c
                lk = key + (("le", repr(ub)),)
                lines.append(f"{self.name}_bucket{_fmt_labels(lk)} {cum}")
            lk = key + (("le", "+Inf"),)
            lines.append(f"{self.name}_bucket{_fmt_labels(lk)} {self._totals[key]}")
            lines.append(f"{self.name}_sum{_fmt_labels(key)} {self._sums[key]}")
            lines.append(f"{self.name}_count{_fmt_labels(key)} {self._totals[key]}")
        return lines


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class Registry:
    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_, buckets), Histogram)

    def _get_or_create(self, name, factory, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            assert isinstance(m, kind), f"metric {name} registered as {type(m)}"
            return m

    def render(self) -> str:
        lines: list[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> list[tuple[str, str, list[tuple[dict, float]]]]:
        """Point-in-time numeric view of every metric, for the metric
        self-scrape (utils/self_trace.py MetricScrapeTask): a list of
        (name, kind, [(labels, value)]) with histograms expanded into
        Prometheus-convention `_bucket` (cumulative, `le` label) / `_sum`
        / `_count` series — the exact series a real Prometheus scrape of
        /metrics would store, so PromQL over the self-scraped tables
        behaves like PromQL over an external scrape."""
        with self._lock:
            metrics_items = list(self._metrics.items())
        out: list[tuple[str, str, list[tuple[dict, float]]]] = []
        for name, m in metrics_items:
            if isinstance(m, Histogram):
                buckets: list[tuple[dict, float]] = []
                sums: list[tuple[dict, float]] = []
                counts: list[tuple[dict, float]] = []
                with m._lock:
                    keys = list(m._counts)
                    for key in keys:
                        labels = dict(key)
                        cum = 0
                        for ub, c in zip(m.buckets, m._counts[key]):
                            cum += c
                            buckets.append(({**labels, "le": repr(ub)}, float(cum)))
                        buckets.append(({**labels, "le": "+Inf"}, float(m._totals[key])))
                        sums.append((labels, float(m._sums[key])))
                        counts.append((labels, float(m._totals[key])))
                if counts:
                    out.append((f"{name}_bucket", "histogram", buckets))
                    out.append((f"{name}_sum", "histogram", sums))
                    out.append((f"{name}_count", "histogram", counts))
                continue
            kind = "gauge" if isinstance(m, Gauge) else "counter"
            with m._lock:
                entries = [(dict(key), float(v)) for key, v in m._values.items()]
            if entries:
                out.append((name, kind, entries))
        return out


REGISTRY = Registry()

# Core engine metrics, named after the reference's (mito2/src/metrics.rs).
WRITE_ROWS_TOTAL = REGISTRY.counter("greptime_mito_write_rows_total", "Rows written")
FLUSH_TOTAL = REGISTRY.counter("greptime_mito_flush_total", "Memtable flushes")
FLUSH_ELAPSED = REGISTRY.histogram("greptime_mito_flush_elapsed", "Flush seconds")
COMPACTION_TOTAL = REGISTRY.counter("greptime_mito_compaction_total", "Compactions")
WRITE_STALL_TOTAL = REGISTRY.counter("greptime_mito_write_stall_total", "Write stalls")
# Pipelined columnar ingest: per-stage timings + WAL frame accounting.
# The stage histograms split a write's wall time between partition-split
# (frontend), WAL append and memtable apply; flush_encode covers the
# Parquet+index encode of one flush.  The frame counters are the
# group-commit observability contract: with ingest.group_commit on,
# wal_frames_total grows SLOWER than writes_total (merged frames), and
# group_writes_total counts the write entries those merged frames carried.
INGEST_SPLIT_MS = REGISTRY.histogram(
    "greptime_ingest_split_ms", "Partition-rule row routing milliseconds per write batch")
INGEST_WAL_MS = REGISTRY.histogram(
    "greptime_ingest_wal_ms", "WAL append milliseconds per write (group appends count once)")
INGEST_MEMTABLE_MS = REGISTRY.histogram(
    "greptime_ingest_memtable_ms", "Memtable apply milliseconds per write")
INGEST_FLUSH_ENCODE_MS = REGISTRY.histogram(
    "greptime_ingest_flush_encode_ms", "Parquet + index encode milliseconds per flush")
INGEST_WRITES_TOTAL = REGISTRY.counter(
    "greptime_ingest_writes_total", "Write requests through the region write path")
INGEST_WAL_FRAMES = REGISTRY.counter(
    "greptime_ingest_wal_frames_total", "WAL frames written (solo or merged group)")
INGEST_WAL_BYTES = REGISTRY.counter(
    "greptime_ingest_wal_bytes_total", "WAL bytes written (frame headers + payload)")
INGEST_GROUP_FRAMES = REGISTRY.counter(
    "greptime_ingest_wal_group_frames_total", "Merged group-commit WAL frames written")
INGEST_GROUP_WRITES = REGISTRY.counter(
    "greptime_ingest_wal_group_writes_total", "Write entries carried by merged group frames")
QUERY_ELAPSED = REGISTRY.histogram("greptime_query_elapsed", "Query seconds")
TPU_LOWERED_TOTAL = REGISTRY.counter("greptime_query_tpu_lowered_total", "Plans lowered to TPU")
TPU_FALLBACK_TOTAL = REGISTRY.counter("greptime_query_tpu_fallback_total", "Plans that fell back to CPU")
TPU_ROUTED_TO_CPU = REGISTRY.counter("greptime_query_tpu_routed_cpu_total", "Lowerable plans routed to CPU by the cost model")
TILE_CACHE_HITS = REGISTRY.counter("greptime_tile_cache_hits_total", "HBM tile cache hits (files)")
TILE_CACHE_MISSES = REGISTRY.counter("greptime_tile_cache_misses_total", "HBM tile cache builds (files)")
TILE_CACHE_EVICTIONS = REGISTRY.counter("greptime_tile_cache_evictions_total", "HBM tile cache evictions")
TILE_QUERY_ELAPSED = REGISTRY.histogram("greptime_query_tile_elapsed", "Tile-path query seconds")
TILE_LOWERED_TOTAL = REGISTRY.counter("greptime_query_tile_lowered_total", "Queries served from the HBM tile cache")
TILE_READBACK_MS = REGISTRY.histogram("greptime_tile_readback_ms", "Device->host result fetch milliseconds per tile query")
TILE_LIMB_RERUNS = REGISTRY.counter("greptime_tile_limb_reruns_total", "Tile queries rerun in exact f64 after the limb error-bound verdict failed")
AGG_STRATEGY_TOTAL = REGISTRY.counter(
    "greptime_agg_strategy_total",
    "Device group-by dispatches by chosen strategy {strategy=hash|sort}",
)
AGG_HASH_OVERFLOW = REGISTRY.counter(
    "greptime_agg_hash_overflow_total",
    "Hash group-by dispatches whose slot table overflowed (distinct-key "
    "estimate badly low) and fell back to the dense path",
)
TILE_PERSIST_HITS = REGISTRY.counter("greptime_tile_persist_hits_total", "Super-tile consolidations loaded from the persisted store (cold-start skip)")
TILE_PERSIST_WRITES = REGISTRY.counter("greptime_tile_persist_writes_total", "Super-tile consolidations written to the persisted store")
TILE_WINDOW_BUILDS = REGISTRY.counter("greptime_tile_window_builds_total", "Compact window tiles gathered from sorted encodes")
TILE_HOST_FAST_PATH = REGISTRY.counter("greptime_tile_host_fast_path_total", "Selective queries served from the sorted host encode cache")
TILE_STREAM_QUERIES = REGISTRY.counter("greptime_tile_stream_total", "Queries whose working set exceeded the HBM budget, executed region-streamed")
TILE_DELTA_MERGES = REGISTRY.counter(
    "greptime_tile_delta_merges_total",
    "Super-tile entries extended IN PLACE by a flush delta (merge of sorted "
    "runs + on-device plane patch) instead of a from-scratch rebuild",
)
TILE_DELTA_ROWS = REGISTRY.counter(
    "greptime_tile_delta_rows_total",
    "Rows merged into existing super-tiles by delta builds (the O(delta) "
    "post-flush cold contract)",
)
TILE_FUSED_MANIFESTS = REGISTRY.counter(
    "greptime_tile_fused_manifests_total",
    "Plane-requirement manifests recorded by query plans / prewarm for the "
    "fused family build planner",
)
TILE_FUSED_BUILDS = REGISTRY.counter(
    "greptime_tile_fused_builds_total",
    "Fused family builds: one consolidated pass building the UNION of the "
    "family's plane manifests (decode each SST once, encode each column "
    "once, one batched upload)",
)
TILE_FUSED_DECODES_SAVED = REGISTRY.counter(
    "greptime_tile_fused_decodes_saved_total",
    "SST file decodes avoided because the fused family pass already holds "
    "the file's host-encoded columns (per file per build request)",
)
TILE_FUSED_ENCODES_SAVED = REGISTRY.counter(
    "greptime_tile_fused_encodes_saved_total",
    "Per-column host encodes avoided because an earlier family member of "
    "the fused build already encoded the column",
)
TILE_FILE_DECODES = REGISTRY.counter(
    "greptime_tile_file_decodes_total",
    "Real SST Parquet decodes performed by the tile build path — the "
    "fused-build contract is exactly ONE per source file per family build",
)
TILE_BUILD_COALESCED = REGISTRY.counter(
    "greptime_tile_build_coalesced_total",
    "Cold tile builds that did NOT run because an in-flight fused family "
    "build covered them; the waiter adopted the leader's planes",
)
TILE_COLD_SERVES = REGISTRY.counter(
    "greptime_tile_cold_serves_total",
    "Queries answered from the host consolidation by the cold-serve router "
    "while device planes build in the background",
)
TILE_FLUSH_DELTA_FILES = REGISTRY.counter(
    "greptime_tile_flush_delta_files_total",
    "SST files announced to flush listeners as delta notifications",
)
TILE_PIPELINED_BUILDS = REGISTRY.counter(
    "greptime_tile_pipelined_builds_total",
    "Cold super-tile builds whose host encode overlapped device upload "
    "(the three-stage encode/upload/compile pipeline)",
)
TPU_PRECOMPILES = REGISTRY.counter(
    "greptime_tpu_precompile_total",
    "Tile-program compiles started from shape metadata alone, before data "
    "upload finished (pipelined cold path)",
)

# Device-side result finalization + readback accounting (the O(rows_out)
# fetch contract): BYTES are the honest unit on a remote-device link —
# greptime_tile_readback_ms conflates compute with transfer because
# device_get blocks on the async dispatch, so tests and the bench assert
# on bytes.  Dispatch/fetch counters back the one-dispatch-one-fetch
# invariant test.
TPU_READBACK_BYTES = REGISTRY.counter(
    "greptime_tpu_readback_bytes_total",
    "Device->host result bytes fetched per lowered query (the O(rows_out) contract)",
)
TPU_READBACK_MS = REGISTRY.histogram(
    "greptime_tpu_readback_ms",
    "Device->host result fetch milliseconds (includes waiting out the async dispatch)",
)
TPU_READBACK_TRANSFER_MS = REGISTRY.histogram(
    "greptime_tpu_readback_transfer_ms",
    "Device->host transfer milliseconds of the result fetch (wire/link time, "
    "including waiting out the async dispatch on the first slice)",
)
TPU_READBACK_DECODE_MS = REGISTRY.histogram(
    "greptime_tpu_readback_decode_ms",
    "Host-side milliseconds decoding the fetched result buffers into Arrow "
    "rows (unpack, NULL-gate, tag/bucket decode, table assembly)",
)
TPU_READBACK_STREAMED = REGISTRY.counter(
    "greptime_tpu_readback_streamed_total",
    "Result fetches split into chunked device_gets overlapped with host "
    "decode (query.streamed_readback)",
)
TPU_DEVICE_DISPATCHES = REGISTRY.counter(
    "greptime_tpu_device_dispatches_total",
    "Compiled tile programs dispatched (one per lowered query attempt)",
)
TILE_MESH_DISPATCHES = REGISTRY.counter(
    "greptime_tile_mesh_dispatches_total",
    "Tile dispatches executed under shard_map on the regions device mesh "
    "(tile.mesh_devices > 0)",
)
TILE_MESH_DEGRADED = REGISTRY.counter(
    "greptime_tile_mesh_degraded_total",
    "Mesh tile dispatches that failed (collective error / OOM) and "
    "degraded to the single-chip path",
)
TPU_DEVICE_FETCHES = REGISTRY.counter(
    "greptime_tpu_device_fetches_total",
    "Device->host result fetches (one per lowered query attempt)",
)
TQL_TILE_DISPATCHES = REGISTRY.counter(
    "greptime_tql_tile_dispatch_total",
    "TQL range-vector evaluations served warm from device tiles in one "
    "fused dispatch (the tql_tile pass)",
)
TQL_TILE_DEGRADED = REGISTRY.counter(
    "greptime_tql_tile_degraded_total",
    "TQL tile-path attempts that failed (fault tql.tile / device error) "
    "and degraded to the legacy upload-per-query path",
)
TQL_TILE_COLD_SERVES = REGISTRY.counter(
    "greptime_tql_tile_cold_serves_total",
    "Cold TQL queries answered from the legacy scan while their family's "
    "background plane build was scheduled",
)
TPU_DEVICE_FINALIZE = REGISTRY.counter(
    "greptime_tpu_device_finalize_total",
    "Queries whose Sort/Limit/HAVING/compaction ran on device (O(rows_out) readback)",
)
TPU_COMPILE_CACHE_HITS = REGISTRY.counter(
    "greptime_tpu_compile_cache_hits_total",
    "Tile-program builds served from the in-process program cache",
)
TPU_COMPILE_CACHE_MISSES = REGISTRY.counter(
    "greptime_tpu_compile_cache_misses_total",
    "Tile-program builds that traced + compiled fresh",
)
PREWARM_BUILDS = REGISTRY.counter(
    "greptime_tpu_prewarm_builds_total",
    "Regions whose super-tiles/limb planes were built by prewarm (off the query path)",
)
PREWARM_MS = REGISTRY.histogram(
    "greptime_tpu_prewarm_ms",
    "Wall milliseconds spent in prewarm builds",
)
DIST_STATE_QUERIES = REGISTRY.counter("greptime_query_dist_state_total", "Distributed queries merged from shipped states")
COMPACTION_BACKGROUND = REGISTRY.counter("greptime_mito_compaction_background_total", "Background compaction merges")
COMPACTION_FAILED = REGISTRY.counter("greptime_mito_compaction_failed_total", "Compaction rounds that errored")

# Fault-tolerance / tail-tolerance metrics (frontend + metasrv planes).
RETRY_ATTEMPTS_TOTAL = REGISTRY.counter(
    "greptime_retry_attempts_total", "Retry re-attempts under the unified RetryPolicy"
)
ROUTE_REFRESH_TOTAL = REGISTRY.counter(
    "greptime_route_refresh_total", "Region route re-fetches between retry attempts"
)
BREAKER_STATE = REGISTRY.gauge(
    "greptime_breaker_state", "Circuit breaker state per peer (0 closed, 1 open, 2 half-open)"
)
BREAKER_TRIPS_TOTAL = REGISTRY.counter(
    "greptime_breaker_trips_total", "Circuit breaker closed/half-open -> open transitions"
)
BREAKER_SHED_TOTAL = REGISTRY.counter(
    "greptime_breaker_shed_total", "Calls failed fast because the peer's breaker was open"
)
HEDGE_REQUESTS_TOTAL = REGISTRY.counter(
    "greptime_hedge_requests_total", "Hedged duplicate region reads sent to followers"
)
HEDGE_WINS_TOTAL = REGISTRY.counter(
    "greptime_hedge_wins_total", "Hedged reads that returned before the primary"
)
FANOUT_ABANDONED_TOTAL = REGISTRY.counter(
    "greptime_fanout_abandoned_total",
    "In-flight region sub-requests abandoned at deadline expiry (client dropped)",
)
PROCEDURE_RETRIES_TOTAL = REGISTRY.counter(
    "greptime_procedure_step_retries_total", "Procedure steps retried after transient failures"
)
FLOW_MIRROR_TOTAL = REGISTRY.counter(
    "greptime_flow_mirror_total", "Flow mirror batches enqueued to flownodes"
)
FLOW_MIRROR_FAILURES_TOTAL = REGISTRY.counter(
    "greptime_flow_mirror_failures_total", "Flow mirror deliveries that failed an attempt"
)
FLOW_MIRROR_DROPPED_TOTAL = REGISTRY.counter(
    "greptime_flow_mirror_dropped_total", "Flow mirror batches dropped after exhausting retries"
)
FLOW_DEDUPE_TOTAL = REGISTRY.counter(
    "greptime_flow_dedupe_total",
    "Mirrored batches the flownode deduplicated by (source, batch_id) — "
    "applied-but-reply-lost retries that would have double-counted",
)

# Incremental dataflow (flow/dataflow.py): diff-driven map/filter/project/
# join flows with dirty-window recompute.  The fallback counter is the
# observability half of the degradation ladder — a CREATE FLOW that cannot
# take the incremental graph leaves a labeled trace instead of silently
# degrading to periodic batch re-runs.
FLOW_BATCH_FALLBACK_TOTAL = REGISTRY.counter(
    "greptime_flow_batch_fallback_total",
    "CREATE FLOW plans that fell back to periodic batch re-runs "
    "(labels: reason = the first graph-inexpressible feature found)",
)
FLOW_DIFF_BATCHES_TOTAL = REGISTRY.counter(
    "greptime_flow_diff_batches_total",
    "Insert diff batches propagated through dataflow operator graphs",
)
FLOW_DIFF_ROWS_TOTAL = REGISTRY.counter(
    "greptime_flow_diff_rows_total",
    "Diff rows (sum of multiplicities) propagated through dataflow "
    "operator graphs",
)
FLOW_DIRTY_WINDOWS_TOTAL = REGISTRY.counter(
    "greptime_flow_dirty_windows_total",
    "Time windows recomputed by dirty-window dataflow operators "
    "(joins + heavy-aggregate window recompute)",
)
FLOW_EXPIRED_TOTAL = REGISTRY.counter(
    "greptime_flow_expired_total",
    "Diff rows / group states / index windows dropped by flow EXPIRE AFTER",
)
FLOW_DEVICE_DISPATCH_TOTAL = REGISTRY.counter(
    "greptime_flow_device_dispatch_total",
    "Flow window recomputes whose aggregate state rebuild dispatched "
    "through the device tile path (materialized-view maintenance riding "
    "the TPU)",
)

# Follower freshness (bounded-staleness replicas): per-region lag gauges
# exported by the follower's own engine, and the hedge/placement/pruning
# counters that ride on them.
FOLLOWER_LAG_ENTRIES = REGISTRY.gauge(
    "greptime_follower_lag_entries",
    "WAL entries a follower region has not yet replayed (best-effort: the "
    "log head is observed at sync time)",
)
FOLLOWER_LAG_MS = REGISTRY.gauge(
    "greptime_follower_lag_ms",
    "Milliseconds since a follower region's last successful WAL-tail sync "
    "(grows monotonically while the sync loop is wedged or disabled)",
)
FOLLOWER_SYNC_TOTAL = REGISTRY.counter(
    "greptime_follower_sync_total", "Follower WAL-tail sync rounds completed"
)
FOLLOWER_SYNC_FAILURES_TOTAL = REGISTRY.counter(
    "greptime_follower_sync_failures_total",
    "Follower sync rounds that failed (transient WAL/manifest weather)",
)
FOLLOWER_MANIFEST_REFRESH_TOTAL = REGISTRY.counter(
    "greptime_follower_manifest_refresh_total",
    "Follower manifest-view refreshes taken because the leader's manifest "
    "version advanced (flush/compaction/truncate/alter)",
)
HEDGE_SKIPPED_STALE_TOTAL = REGISTRY.counter(
    "greptime_hedge_skipped_stale_total",
    "Hedge candidates skipped because the follower's lag exceeded "
    "replica.max_lag_ms",
)
FANOUT_CANCELLED_TOTAL = REGISTRY.counter(
    "greptime_fanout_cancelled_total",
    "In-flight Flight calls best-effort cancelled at deadline expiry "
    "(feature-detected reader cancel, channel close for calls still "
    "waiting on the stream; detach-and-drop is the fallback)",
)
FOLLOWER_PLACEMENTS_TOTAL = REGISTRY.counter(
    "greptime_follower_placements_total",
    "Followers opened by the metasrv placement selector",
)
FOLLOWER_GC_TOTAL = REGISTRY.counter(
    "greptime_follower_gc_total",
    "Orphaned followers (dead node / now-the-leader) garbage-collected "
    "from region routes by the placement pass",
)
WAL_PRUNE_HELD_TOTAL = REGISTRY.counter(
    "greptime_wal_prune_held_total",
    "Shared-WAL segments whose deletion was held back by a follower "
    "replay low-watermark",
)

# Multi-tenant admission control + overload survival (utils/admission.py,
# the tile executor's coalescing/HBM feedback in parallel/tile_cache.py).
ADMISSION_QUEUE_DEPTH = REGISTRY.gauge(
    "greptime_admission_queue_depth",
    "Statements currently queued by the admission scheduler, per tenant",
)
ADMISSION_RUNNING = REGISTRY.gauge(
    "greptime_admission_running",
    "Statements currently admitted and executing under the admission gate",
)
ADMISSION_WAIT_MS = REGISTRY.histogram(
    "greptime_admission_wait_ms",
    "Milliseconds a statement waited in the admission queue before running",
)
ADMISSION_ADMITTED_TOTAL = REGISTRY.counter(
    "greptime_admission_admitted_total",
    "Statements admitted by the scheduler (immediately or after queueing)",
)
ADMISSION_SHED_TOTAL = REGISTRY.counter(
    "greptime_admission_shed_total",
    "Statements shed by the admission layer (labels: reason = "
    "queue_depth | deadline | wait_timeout | injected)",
)
DISPATCH_COALESCED_TOTAL = REGISTRY.counter(
    "greptime_dispatch_coalesced_total",
    "Tile queries served by attaching to another query's in-flight "
    "device dispatch (leader executes once, waiters share the result)",
)
DISPATCH_COALESCE_LEADERS_TOTAL = REGISTRY.counter(
    "greptime_dispatch_coalesce_leader_total",
    "Tile dispatches that executed as a coalition leader with >= 1 waiter",
)
QUERY_BATCH_DISPATCHES_TOTAL = REGISTRY.counter(
    "greptime_query_batch_dispatches_total",
    "Fused mega-dispatches executed by the cross-query batcher (>= 2 "
    "distinct warm queries sharing one packed device readback)",
)
QUERY_BATCH_MEMBERS_TOTAL = REGISTRY.counter(
    "greptime_query_batch_members_total",
    "Queries whose result came home inside a batched mega-readback "
    "(members per dispatch = members_total / dispatches_total)",
)
QUERY_BATCH_FUSED_DISPATCHES_TOTAL = REGISTRY.counter(
    "greptime_batch_fused_dispatches_total",
    "Batch ticks whose members executed as ONE mega-fused XLA invocation "
    "(shared plane scan, per-member masks/folds/finalize fused branches)",
)
QUERY_BATCH_FUSE_MEMBERS = REGISTRY.histogram(
    "greptime_batch_fuse_members",
    "Members fused into one mega-program invocation, per batch tick",
    buckets=(2, 3, 4, 6, 8, 12, 16, 24, 32),
)
QUERY_BATCH_FUSE_DEGRADED_TOTAL = REGISTRY.counter(
    "greptime_batch_fuse_degraded_total",
    "Batch ticks that fell back to per-member dispatches after a fused "
    "capture/trace/compile/dispatch failure (served correctly, unfused)",
)
QUERY_BATCH_RESULT_CACHE_HITS_TOTAL = REGISTRY.counter(
    "greptime_query_batch_result_cache_hits_total",
    "Warm queries served from the windowed result cache with zero "
    "device dispatch (key: plan fingerprint + literal digest + region "
    "versions + aligned window)",
)
QUERY_BATCH_RESULT_CACHE_EVICTIONS_TOTAL = REGISTRY.counter(
    "greptime_query_batch_result_cache_evictions_total",
    "Result-cache entries dropped: LRU pressure against "
    "batch.result_cache_mb or region invalidation on flush/delta",
)
HBM_EXHAUSTED_TOTAL = REGISTRY.counter(
    "greptime_hbm_exhausted_total",
    "RESOURCE_EXHAUSTED dispatch failures absorbed by the closed HBM "
    "feedback loop (emergency release + halve-chunk retry)",
)
HBM_CHUNK_ROWS = REGISTRY.gauge(
    "greptime_hbm_chunk_rows",
    "Current tile chunk size in rows (halved by the HBM feedback loop "
    "after RESOURCE_EXHAUSTED; never below admission.min_chunk_rows)",
)
HBM_PROBE_FREE_BYTES = REGISTRY.gauge(
    "greptime_hbm_probe_free_bytes",
    "Free device memory measured by the startup allocation probe "
    "(0 = probe unavailable on this backend)",
)
GOVERNOR_GATE_WAIT_MS = REGISTRY.histogram(
    "greptime_memory_gate_wait_ms",
    "Milliseconds a statement blocked in MemoryGovernor's concurrency "
    "gate before a slot freed (deadline-clipped bounded wait)",
)
WRITE_HEDGE_TOTAL = REGISTRY.counter(
    "greptime_write_hedge_total",
    "Writes that met an open breaker and successfully hedged to the "
    "failover candidate (breaker.write_hedge; metasrv accepted the "
    "frontend-initiated failover)",
)
WRITE_HEDGE_REFUSED_TOTAL = REGISTRY.counter(
    "greptime_write_hedge_refused_total",
    "Write-hedge failover requests the metasrv refused (node lease still "
    "live / procedure already running / metasrv churn): the write sheds "
    "like a read",
)
FAILOVER_REQUESTED_TOTAL = REGISTRY.counter(
    "greptime_failover_requested_total",
    "Frontend-initiated failovers the metasrv accepted and ran "
    "(breaker-aware write routing)",
)

# Self-observability loop (utils/tracing.py ring exporter +
# utils/self_trace.py writer/scrape): the database tracing itself into its
# own trace store, slow-query log and metric engine.
TRACE_SPANS_DROPPED = REGISTRY.counter(
    "greptime_trace_spans_dropped_total",
    "Spans shed by the exporter ring buffer (oldest-first) because the "
    "self-trace writer fell behind or self-tracing is off",
)
TRACE_SAMPLED_TOTAL = REGISTRY.counter(
    "greptime_trace_sampled_total",
    "Tail-sampling decisions per traced statement (labels: decision = "
    "slow | error | sampled | dropped)",
)
SELF_TRACE_ROWS = REGISTRY.counter(
    "greptime_self_trace_rows_total",
    "Span rows the SelfTraceWriter wrote into the own trace table",
)
SELF_TRACE_WRITE_FAILURES = REGISTRY.counter(
    "greptime_self_trace_write_failures_total",
    "Self-trace write batches dropped after a write failure (best-effort "
    "by contract: a trace-write failure never fails the traced query)",
)
SELF_SCRAPE_ROWS = REGISTRY.counter(
    "greptime_self_scrape_rows_total",
    "Metric samples the self-scrape task wrote into the metric engine",
)
SELF_SCRAPE_RUNS = REGISTRY.counter(
    "greptime_self_scrape_runs_total",
    "Completed /metrics self-scrape rounds",
)

# Device flight recorder (utils/flight_recorder.py): the per-dispatch
# introspection ring behind information_schema.device_dispatches,
# EXPLAIN ANALYZE's device-stage split and /debug/tile.
RECORDER_RECORDS = REGISTRY.counter(
    "greptime_recorder_records_total",
    "Dispatch records appended to the flight-recorder ring",
)
RECORDER_DROPPED = REGISTRY.counter(
    "greptime_recorder_dropped_total",
    "Flight-recorder records evicted oldest-first by the bounded ring",
)
RECORDER_ERRORS = REGISTRY.counter(
    "greptime_recorder_errors_total",
    "Flight-recorder emit failures swallowed (recording is best-effort "
    "by contract: a recorder failure never fails the recorded query)",
)

# Elastic balancer (distributed/balancer.py): load-driven region
# split/merge/migration decisions behind information_schema.region_balance.
BALANCE_DECISIONS_TOTAL = REGISTRY.counter(
    "greptime_balance_decisions_total",
    "Balancer decisions that cleared hysteresis and were enacted "
    "(labels: decision = split | merge | migrate)",
)
BALANCE_SPLITS_TOTAL = REGISTRY.counter(
    "greptime_balance_splits_total",
    "Hot-region splits the balancer drove through RepartitionProcedure",
)
BALANCE_MERGES_TOTAL = REGISTRY.counter(
    "greptime_balance_merges_total",
    "Cold-sibling merges the balancer drove through RepartitionProcedure",
)
BALANCE_MIGRATIONS_TOTAL = REGISTRY.counter(
    "greptime_balance_migrations_total",
    "Region migrations the balancer drove off overloaded datanodes",
)
BALANCE_SKIPPED_HYSTERESIS_TOTAL = REGISTRY.counter(
    "greptime_balance_skipped_hysteresis_total",
    "Decisions deferred by hysteresis (EWMA dwell not yet met, table "
    "cooling down after a recent decision, or a conflicting procedure "
    "holds the region lock)",
)

# Wire-level remote backends (remote/): etcd v3 / Kafka / S3 adapters
# routed through the shared wire resilience layer.
REMOTE_CALLS_TOTAL = REGISTRY.counter(
    "greptime_remote_calls_total",
    "Remote backend wire calls issued (labels: backend = etcd | kafka | "
    "s3, op = protocol-level operation name)",
)
REMOTE_ERRORS_TOTAL = REGISTRY.counter(
    "greptime_remote_errors_total",
    "Remote backend wire calls that failed after exhausting the retry "
    "policy (labels: backend, op)",
)
REMOTE_RETRIES_TOTAL = REGISTRY.counter(
    "greptime_remote_retries_total",
    "Transient remote-call failures that were retried by the wire layer "
    "(labels: backend)",
)
REMOTE_CALL_MS = REGISTRY.histogram(
    "greptime_remote_call_elapsed_ms",
    "End-to-end remote call latency in milliseconds, retries included "
    "(labels: backend)",
)
REMOTE_THROTTLED_TOTAL = REGISTRY.counter(
    "greptime_remote_throttled_total",
    "Server throttle responses honored with a Retry-After style backoff "
    "(S3 503 SlowDown; labels: backend)",
)
OTLP_SELF_EXPORT_SPANS = REGISTRY.counter(
    "greptime_otlp_self_export_spans_total",
    "Self-observability spans shipped over the wire as OTLP protobuf by "
    "roles with no local writer (bare datanodes)",
)
OTLP_SELF_EXPORT_FAILURES = REGISTRY.counter(
    "greptime_otlp_self_export_failures_total",
    "OTLP self-export batches dropped after the wire layer gave up "
    "(export is best-effort: a full buffer never blocks the hot path)",
)

# Device health supervisor (utils/device_health.py): bounded device calls,
# wedge detection, quarantine + heal behind
# information_schema.device_health.
DEVICE_HEALTH_TRANSITIONS = REGISTRY.counter(
    "greptime_device_health_transitions_total",
    "Device health state-machine transitions (labels: to = HEALTHY | "
    "SUSPECT | QUARANTINED | PROBING)",
)
DEVICE_HEALTH_STATE = REGISTRY.gauge(
    "greptime_device_health_state",
    "Current per-device health state (labels: device; 0 healthy, "
    "1 suspect, 2 quarantined, 3 probing)",
)
DEVICE_HEALTH_ABANDONED = REGISTRY.counter(
    "greptime_device_health_abandoned_calls_total",
    "Supervised device calls abandoned at their hard deadline — the "
    "future detached and the worker thread written off, since a wedged "
    "native call cannot be cancelled (labels: kind = upload | dispatch | "
    "readback | mesh | memory_stats | probe)",
)
DEVICE_HEALTH_QUARANTINES = REGISTRY.counter(
    "greptime_device_health_quarantines_total",
    "Devices quarantined (abandoned call, or error_threshold consecutive "
    "raised device errors)",
)
DEVICE_HEALTH_HEALS = REGISTRY.counter(
    "greptime_device_health_heals_total",
    "Quarantined devices re-admitted after probe_successes consecutive "
    "in-deadline ghost dispatches",
)
DEVICE_HEALTH_PROBES = REGISTRY.counter(
    "greptime_device_health_probes_total",
    "Heal-prober ghost dispatches against quarantined devices "
    "(labels: result = ok | fail)",
)
DEVICE_WORKER_REFILLS = REGISTRY.counter(
    "greptime_device_worker_refills_total",
    "Replacement device-call worker threads spawned after an abandonment "
    "wrote the previous worker off (the supervisor's bounded thread leak)",
)
TILE_HEALTH_INVALIDATIONS = REGISTRY.counter(
    "greptime_tile_health_invalidations_total",
    "Tile-cache device-plane drops triggered by a device-health "
    "generation change (quarantine or heal): entries rebuild on the "
    "surviving device set",
)
