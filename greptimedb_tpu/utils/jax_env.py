"""JAX runtime configuration shared by every kernel entry point.

A time-series database computes on int64 timestamps (epoch-ms overflows
int32), so x64 must be on wherever the kernels run — including the real
TPU chip, where jax defaults to x32 and would silently truncate both the
timestamps and the int64 sentinels in the segmented kernels (observed as
an OverflowError in ops/rate.py on the axon platform).  Value columns stay
float32/bfloat16 by explicit dtype choice in the kernels; this only widens
the default so int64/float64 requests mean what they say.
"""

from __future__ import annotations

_done = False


def ensure_x64():
    global _done
    if _done:
        return
    import jax

    jax.config.update("jax_enable_x64", True)
    _done = True


_cache_done = False


def ensure_compilation_cache(path: str | None = None):
    """Persistent XLA compilation cache: query-plan shapes compile once per
    machine, not once per process (cold-query latency is dominated by XLA
    compilation; the reference's equivalent is DataFusion having no
    compilation step at all, so cold starts must not regress vs it)."""
    global _cache_done
    if _cache_done:
        return
    import os

    import jax

    if path is None:
        path = os.environ.get(
            "GREPTIMEDB_TPU_XLA_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "greptimedb_tpu_xla"),
        )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        pass
    _cache_done = True
