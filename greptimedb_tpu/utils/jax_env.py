"""JAX runtime configuration shared by every kernel entry point.

A time-series database computes on int64 timestamps (epoch-ms overflows
int32), so x64 must be on wherever the kernels run — including the real
TPU chip, where jax defaults to x32 and would silently truncate both the
timestamps and the int64 sentinels in the segmented kernels (observed as
an OverflowError in ops/rate.py on the axon platform).  Value columns stay
float32/bfloat16 by explicit dtype choice in the kernels; this only widens
the default so int64/float64 requests mean what they say.
"""

from __future__ import annotations

_done = False


def ensure_x64():
    global _done
    if _done:
        return
    import jax

    jax.config.update("jax_enable_x64", True)
    _done = True
