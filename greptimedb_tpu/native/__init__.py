"""ctypes bindings for the native runtime library.

Auto-builds `libgreptime_native.so` with g++ on first import if missing
(and a toolchain exists); every entry point has a pure-Python fallback so
the package works without the native lib — but the hot paths (WAL recovery
scan, line-protocol tokenize, crc32) run native when available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_DIR = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_DIR, "libgreptime_native.so")
_lib = None


def _try_build() -> bool:
    src = os.path.join(_DIR, "src", "greptime_native.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", _LIB_PATH, src],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def load() -> ctypes.CDLL | None:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and not _try_build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.gt_crc32.restype = ctypes.c_uint32
    lib.gt_crc32.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32]
    lib.gt_wal_scan.restype = ctypes.c_int64
    lib.gt_wal_scan.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
    ]
    lib.gt_lp_tokenize.restype = ctypes.c_int64
    lib.gt_lp_tokenize.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
    ]
    for name in (
        "gt_snappy_uncompressed_length",
        "gt_snappy_decompress",
        "gt_snappy_compress",
        "gt_snappy_max_compressed_length",
        "gt_lp_parse_homogeneous",
    ):
        if not hasattr(lib, name):
            # Stale .so missing newer entry points: rebuild once.
            _lib = None
            try:
                os.remove(_LIB_PATH)
            except OSError:
                return None
            if not _try_build():
                return None
            return load()
    lib.gt_snappy_uncompressed_length.restype = ctypes.c_int64
    lib.gt_snappy_uncompressed_length.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.gt_snappy_decompress.restype = ctypes.c_int64
    lib.gt_snappy_decompress.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_char),
        ctypes.c_int64,
    ]
    lib.gt_snappy_compress.restype = ctypes.c_int64
    lib.gt_snappy_compress.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_char),
        ctypes.c_int64,
    ]
    lib.gt_snappy_max_compressed_length.restype = ctypes.c_int64
    lib.gt_snappy_max_compressed_length.argtypes = [ctypes.c_int64]
    _lib = lib
    return lib


def available() -> bool:
    return load() is not None


def crc32(data: bytes, seed: int = 0) -> int:
    lib = load()
    if lib is None:
        import zlib

        return zlib.crc32(data, seed)
    return lib.gt_crc32(data, len(data), seed)


def wal_scan(buf: bytes, max_entries: int = 1 << 20) -> list[tuple[int, int, int]]:
    """Scan WAL frames -> [(payload_offset, payload_len, entry_id)]."""
    lib = load()
    if lib is None:
        return _wal_scan_py(buf, max_entries)
    out = (ctypes.c_int64 * (3 * max_entries))()
    n = lib.gt_wal_scan(buf, len(buf), out, max_entries)
    return [(out[i * 3], out[i * 3 + 1], out[i * 3 + 2]) for i in range(n)]


def _wal_scan_py(buf: bytes, max_entries: int):
    import struct
    import zlib

    header = struct.Struct("<IIQ")
    out, pos = [], 0
    while len(out) < max_entries and pos + header.size <= len(buf):
        length, crc, entry_id = header.unpack_from(buf, pos)
        payload_start = pos + header.size
        if payload_start + length > len(buf):
            break
        payload = buf[payload_start : payload_start + length]
        if zlib.crc32(payload) != crc:
            break
        out.append((payload_start, length, entry_id))
        pos = payload_start + length
    return out


# Token kinds from greptime_native.cpp (kind >= 100 means "has escapes").
TOK_MEASUREMENT = 0
TOK_TAG_KEY = 1
TOK_TAG_VAL = 2
TOK_FIELD_KEY = 3
TOK_FIELD_FLOAT = 4
TOK_FIELD_INT = 5
TOK_FIELD_STR = 6
TOK_FIELD_BOOL_T = 7
TOK_FIELD_BOOL_F = 8
TOK_TIMESTAMP = 9
TOK_LINE_END = 10


def lp_parse_homogeneous(buf: bytes, mult_num: int, mult_den: int,
                         max_tags: int = 16, max_fields: int = 32):
    """Columnar parse of a HOMOGENEOUS line-protocol batch (one
    measurement, fixed tag/float-field keys, timestamps present).
    Returns (measurement, tag_keys, field_keys, ts int64[n],
    fields float64[n, n_fields], tag_spans int64[n, n_tags, 2]) or None
    (unavailable / batch not homogeneous — fall back to the tokenizer)."""
    lib = load()
    if lib is None or not hasattr(lib, "gt_lp_parse_homogeneous"):
        return None
    import numpy as np

    # size outputs from LINE 1's shape (every later line must match it or
    # the parse bails anyway) — sizing by the caps wasted ~500 MB on
    # million-line single-field batches
    first = buf.split(b"\n", 1)[0]
    head = first.split(b" ", 1)
    max_tags = min(max_tags, max(head[0].count(b","), 1))
    if len(head) > 1:
        max_fields = min(max_fields, max(head[1].count(b",") + 2, 2))
    max_lines = buf.count(b"\n") + 2
    ts = np.empty(max_lines, dtype=np.int64)
    fields = np.empty(max_lines * max_fields, dtype=np.float64)
    tag_spans = np.empty(max_lines * max_tags * 2, dtype=np.int64)
    shape = np.zeros(4 + 2 * max_tags + 2 * max_fields, dtype=np.int64)
    fn = lib.gt_lp_parse_homogeneous
    fn.restype = ctypes.c_int64
    n = fn(
        buf, ctypes.c_int64(len(buf)),
        ctypes.c_int64(mult_num), ctypes.c_int64(mult_den),
        ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        fields.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        tag_spans.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(max_lines), ctypes.c_int64(max_tags),
        ctypes.c_int64(max_fields),
        shape.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if n <= 0:
        return None
    n_tags, n_fields = int(shape[0]), int(shape[1])
    measurement = buf[shape[2]:shape[3]].decode()
    tag_keys = [
        buf[shape[4 + t * 2]:shape[4 + t * 2 + 1]].decode() for t in range(n_tags)
    ]
    base = 4 + max_tags * 2
    field_keys = [
        buf[shape[base + f * 2]:shape[base + f * 2 + 1]].decode()
        for f in range(n_fields)
    ]
    return (
        measurement, tag_keys, field_keys,
        ts[:n].copy(),
        fields.reshape(max_lines, max_fields)[:n, :n_fields].copy(),
        tag_spans.reshape(max_lines, max_tags, 2)[:n, :n_tags].copy(),
    )


def lp_tokenize(buf: bytes, max_tokens: int | None = None):
    """Tokenize line protocol -> [(kind, start, end)] or None if the native
    lib is unavailable (caller falls back to the Python parser)."""
    lib = load()
    if lib is None:
        return None
    if max_tokens is None:
        max_tokens = max(64, buf.count(b"\n") * 16 + 64)
    out = (ctypes.c_int64 * (3 * max_tokens))()
    n = lib.gt_lp_tokenize(buf, len(buf), out, max_tokens)
    if n < 0:
        from ..utils.errors import InvalidArgumentsError

        raise InvalidArgumentsError(f"bad line protocol near offset {-(n + 1)}")
    return [(out[i * 3], out[i * 3 + 1], out[i * 3 + 2]) for i in range(n)]


# ---- snappy (Prometheus remote write/read bodies) --------------------------


class SnappyError(ValueError):
    pass


def snappy_decompress(data: bytes) -> bytes:
    lib = load()
    if lib is None:
        return _snappy_decompress_py(data)
    n = lib.gt_snappy_uncompressed_length(data, len(data))
    # Snappy's worst-case expansion is a 2-byte copy element emitting 64
    # bytes (32x); a preamble claiming more than that is hostile — reject
    # before allocating (the length is attacker-controlled input).
    if n < 0 or n > len(data) * 32 + 64:
        raise SnappyError("bad snappy preamble")
    out = ctypes.create_string_buffer(n)
    got = lib.gt_snappy_decompress(data, len(data), out, n)
    if got < 0:
        raise SnappyError(f"snappy decompress failed (code {got})")
    return out.raw[:got]


def snappy_compress(data: bytes) -> bytes:
    lib = load()
    if lib is None:
        return _snappy_compress_py(data)
    cap = lib.gt_snappy_max_compressed_length(len(data))
    out = ctypes.create_string_buffer(cap)
    got = lib.gt_snappy_compress(data, len(data), out, cap)
    if got < 0:
        raise SnappyError(f"snappy compress failed (code {got})")
    return out.raw[:got]


def _uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    v, shift = 0, 0
    while pos < len(buf):
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not (b & 0x80):
            return v, pos
        shift += 7
        if shift > 63:
            break
    raise SnappyError("bad varint")


def _snappy_decompress_py(data: bytes) -> bytes:
    expect, ip = _uvarint(data, 0)
    if expect > len(data) * 32 + 64:
        raise SnappyError("bad snappy preamble")
    out = bytearray()
    n = len(data)
    while ip < n:
        tag = data[ip]
        ip += 1
        kind = tag & 3
        if kind == 0:
            lit_len = (tag >> 2) + 1
            if lit_len > 60:
                extra = lit_len - 60
                if ip + extra > n:
                    raise SnappyError("truncated literal length")
                lit_len = int.from_bytes(data[ip : ip + extra], "little") + 1
                ip += extra
            if ip + lit_len > n:
                raise SnappyError("truncated literal")
            out += data[ip : ip + lit_len]
            ip += lit_len
        else:
            if kind == 1:
                if ip + 1 > n:
                    raise SnappyError("truncated copy")
                cp_len = ((tag >> 2) & 7) + 4
                offset = ((tag >> 5) << 8) | data[ip]
                ip += 1
            elif kind == 2:
                if ip + 2 > n:
                    raise SnappyError("truncated copy")
                cp_len = (tag >> 2) + 1
                offset = int.from_bytes(data[ip : ip + 2], "little")
                ip += 2
            else:
                if ip + 4 > n:
                    raise SnappyError("truncated copy")
                cp_len = (tag >> 2) + 1
                offset = int.from_bytes(data[ip : ip + 4], "little")
                ip += 4
            if offset == 0 or offset > len(out):
                raise SnappyError("bad copy offset")
            for _ in range(cp_len):  # may overlap its own output
                out.append(out[-offset])
    if len(out) != expect:
        raise SnappyError("snappy length mismatch")
    return bytes(out)


def _snappy_compress_py(data: bytes) -> bytes:
    """Literal-only encoding — valid snappy, zero compression (fallback)."""
    out = bytearray()
    v = len(data)
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 65536]
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        else:
            out.append(61 << 2)
            out += n.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)
