// Native runtime hot paths.
//
// The reference implements its whole runtime in Rust; these are the C++
// equivalents for the paths where Python overhead matters most:
//   - crc32 (zlib polynomial, slice-by-8): WAL frame checksums
//   - wal_scan: frame-walk a WAL buffer, validating lengths + CRCs and
//     reporting entry offsets (region open replays call this per region;
//     reference raft-engine does its recovery scan in native code too)
//   - lp_tokenize: InfluxDB line-protocol tokenizer emitting token offsets
//     (measurement/tag/field/timestamp spans) so Python only slices —
//     the ingest hot loop (reference servers/src/influxdb.rs + row_writer)
//
// Exposed with a plain C ABI for ctypes.  Build: `make` in native/.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------- crc32 ----

static uint32_t crc_table[8][256];
static bool crc_init_done = false;

static void crc_init() {
    if (crc_init_done) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc_table[0][i];
        for (int s = 1; s < 8; s++) {
            c = crc_table[0][c & 0xFF] ^ (c >> 8);
            crc_table[s][i] = c;
        }
    }
    crc_init_done = true;
}

uint32_t gt_crc32(const uint8_t* data, size_t len, uint32_t seed) {
    crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    while (len >= 8) {
        c ^= (uint32_t)data[0] | ((uint32_t)data[1] << 8) |
             ((uint32_t)data[2] << 16) | ((uint32_t)data[3] << 24);
        uint32_t hi = (uint32_t)data[4] | ((uint32_t)data[5] << 8) |
                      ((uint32_t)data[6] << 16) | ((uint32_t)data[7] << 24);
        c = crc_table[7][c & 0xFF] ^ crc_table[6][(c >> 8) & 0xFF] ^
            crc_table[5][(c >> 16) & 0xFF] ^ crc_table[4][(c >> 24) & 0xFF] ^
            crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
            crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][(hi >> 24) & 0xFF];
        data += 8;
        len -= 8;
    }
    while (len--) c = crc_table[0][(c ^ *data++) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- wal scan ----

// Frame: [u32 payload_len][u32 crc32(payload)][u64 entry_id][payload]
// Scans up to max_entries frames; writes (offset, payload_len, entry_id)
// triples into out (3 * max_entries int64 slots).  Returns the number of
// valid frames; stops at a torn/corrupt tail like the Python replay().
int64_t gt_wal_scan(const uint8_t* buf, int64_t len, int64_t* out,
                    int64_t max_entries) {
    crc_init();
    int64_t pos = 0, n = 0;
    const int64_t header = 16;
    while (n < max_entries && pos + header <= len) {
        uint32_t payload_len, crc;
        uint64_t entry_id;
        memcpy(&payload_len, buf + pos, 4);
        memcpy(&crc, buf + pos + 4, 4);
        memcpy(&entry_id, buf + pos + 8, 8);
        if (pos + header + (int64_t)payload_len > len) break;  // torn tail
        if (gt_crc32(buf + pos + header, payload_len, 0) != crc) break;
        out[n * 3 + 0] = pos + header;
        out[n * 3 + 1] = (int64_t)payload_len;
        out[n * 3 + 2] = (int64_t)entry_id;
        pos += header + payload_len;
        n++;
    }
    return n;
}

// ---------------------------------------------------- line protocol -------

// Token kinds emitted by lp_tokenize.
enum TokKind : int32_t {
    TOK_MEASUREMENT = 0,
    TOK_TAG_KEY = 1,
    TOK_TAG_VAL = 2,
    TOK_FIELD_KEY = 3,
    TOK_FIELD_FLOAT = 4,
    TOK_FIELD_INT = 5,
    TOK_FIELD_STR = 6,
    TOK_FIELD_BOOL_T = 7,
    TOK_FIELD_BOOL_F = 8,
    TOK_TIMESTAMP = 9,
    TOK_LINE_END = 10,
    TOK_ERROR = 11,
};

// Tokenizes `buf` into (kind, start, end) triples written to out
// (3 * max_tokens int64 slots, kind stored as int64).  Handles escapes
// (\,  \space  \= inside identifiers) and double-quoted strings with \".
// Escaped spans keep their backslashes; Python unescapes only when a
// backslash was seen (flagged by kind += 100).
// Returns token count, or -(1+offset) on error.
int64_t gt_lp_tokenize(const uint8_t* buf, int64_t len, int64_t* out,
                       int64_t max_tokens) {
    int64_t n = 0;
    int64_t i = 0;
    auto emit = [&](int64_t kind, int64_t s, int64_t e) -> bool {
        if (n >= max_tokens) return false;
        out[n * 3] = kind; out[n * 3 + 1] = s; out[n * 3 + 2] = e;
        n++;
        return true;
    };
    while (i < len) {
        // skip blank lines / comments
        while (i < len && (buf[i] == '\n' || buf[i] == '\r')) i++;
        if (i >= len) break;
        if (buf[i] == '#') {
            while (i < len && buf[i] != '\n') i++;
            continue;
        }
        // measurement (to unescaped ',' or ' ')
        int64_t start = i;
        bool escaped = false;
        while (i < len && buf[i] != ',' && buf[i] != ' ' && buf[i] != '\n') {
            if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
            else i++;
        }
        if (i >= len || buf[i] == '\n') return -(1 + start);
        if (!emit(TOK_MEASUREMENT + (escaped ? 100 : 0), start, i)) return n;
        // tags
        while (i < len && buf[i] == ',') {
            i++;
            start = i; escaped = false;
            while (i < len && buf[i] != '=') {
                if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                else i++;
            }
            if (i >= len) return -(1 + start);
            if (!emit(TOK_TAG_KEY + (escaped ? 100 : 0), start, i)) return n;
            i++;  // '='
            start = i; escaped = false;
            while (i < len && buf[i] != ',' && buf[i] != ' ') {
                if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                else i++;
            }
            if (!emit(TOK_TAG_VAL + (escaped ? 100 : 0), start, i)) return n;
        }
        if (i >= len || buf[i] != ' ') return -(1 + i);
        while (i < len && buf[i] == ' ') i++;
        // fields
        bool more_fields = true;
        while (more_fields) {
            start = i; escaped = false;
            while (i < len && buf[i] != '=') {
                if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                else i++;
            }
            if (i >= len) return -(1 + start);
            if (!emit(TOK_FIELD_KEY + (escaped ? 100 : 0), start, i)) return n;
            i++;  // '='
            if (i < len && buf[i] == '"') {
                i++;
                start = i; escaped = false;
                while (i < len && buf[i] != '"') {
                    if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                    else i++;
                }
                if (i >= len) return -(1 + start);
                if (!emit(TOK_FIELD_STR + (escaped ? 100 : 0), start, i)) return n;
                i++;  // closing quote
            } else {
                start = i;
                while (i < len && buf[i] != ',' && buf[i] != ' ' && buf[i] != '\n') i++;
                int64_t end = i;
                if (end == start) return -(1 + start);
                uint8_t last = buf[end - 1];
                int64_t kind;
                if (end - start == 1 && (buf[start] == 't' || buf[start] == 'T'))
                    kind = TOK_FIELD_BOOL_T;
                else if (end - start == 1 && (buf[start] == 'f' || buf[start] == 'F'))
                    kind = TOK_FIELD_BOOL_F;
                else if ((end - start == 4 && !strncmp((const char*)buf + start, "true", 4)))
                    kind = TOK_FIELD_BOOL_T;
                else if ((end - start == 5 && !strncmp((const char*)buf + start, "false", 5)))
                    kind = TOK_FIELD_BOOL_F;
                else if (last == 'i' || last == 'u')
                    kind = TOK_FIELD_INT;
                else
                    kind = TOK_FIELD_FLOAT;
                if (!emit(kind, start, end)) return n;
            }
            if (i < len && buf[i] == ',') { i++; continue; }
            more_fields = false;
        }
        // optional timestamp
        if (i < len && buf[i] == ' ') {
            while (i < len && buf[i] == ' ') i++;
            start = i;
            while (i < len && buf[i] != '\n' && buf[i] != ' ' && buf[i] != '\r') i++;
            if (i > start) {
                if (!emit(TOK_TIMESTAMP, start, i)) return n;
            }
        }
        if (!emit(TOK_LINE_END, i, i)) return n;
        while (i < len && buf[i] != '\n') i++;
    }
    return n;
}

// Homogeneous columnar line-protocol parse (the hot ingest shape: every
// line shares one measurement, the same tag keys in order, the same
// FLOAT field keys, and carries a timestamp — the TSBS/scrape pattern).
// Fills ts (int64, scaled by mult_num/mult_den), fields (row-major
// doubles, n_fields per line) and tag value byte-spans (2 int64 per
// (line, tag)).  Returns the line count, or -1 when the batch does not
// fit the homogeneous shape (caller falls back to the tokenizer path).
int64_t gt_lp_parse_homogeneous(const uint8_t* buf, int64_t len,
                                int64_t mult_num, int64_t mult_den,
                                int64_t* ts_out, double* field_out,
                                int64_t* tag_spans_out,
                                int64_t max_lines, int64_t max_tags,
                                int64_t max_fields,
                                int64_t* shape_out /* [4+2*max_tags+2*max_fields]:
                                n_tags, n_fields, then key spans from line 1 */) {
    int64_t i = 0, n_lines = 0;
    int64_t n_tags = -1, n_fields = -1;
    // first-line layout spans (keys compared by bytes for later lines)
    int64_t tag_key_spans[64][2];
    int64_t field_key_spans[64][2];
    int64_t meas_s = -1, meas_e = -1;
    while (i < len) {
        while (i < len && (buf[i] == '\n' || buf[i] == '\r')) i++;
        if (i >= len) break;
        if (buf[i] == '#') { while (i < len && buf[i] != '\n') i++; continue; }
        if (n_lines >= max_lines) return -1;
        // measurement
        int64_t s = i;
        while (i < len && buf[i] != ',' && buf[i] != ' ') {
            if (buf[i] == '\\') return -1;  // escapes: fallback
            i++;
        }
        if (i >= len) return -1;
        if (meas_s < 0) { meas_s = s; meas_e = i; }
        else if (i - s != meas_e - meas_s ||
                 memcmp(buf + s, buf + meas_s, i - s) != 0) return -1;
        // tags
        int64_t t = 0;
        while (i < len && buf[i] == ',') {
            i++;
            int64_t ks = i;
            while (i < len && buf[i] != '=') {
                if (buf[i] == '\\') return -1;
                i++;
            }
            if (i >= len) return -1;
            int64_t ke = i;
            i++;
            int64_t vs = i;
            while (i < len && buf[i] != ',' && buf[i] != ' ') {
                if (buf[i] == '\\') return -1;
                i++;
            }
            if (t >= max_tags || t >= 64) return -1;
            if (n_tags < 0) { tag_key_spans[t][0] = ks; tag_key_spans[t][1] = ke; }
            else {
                if (t >= n_tags) return -1;
                if (ke - ks != tag_key_spans[t][1] - tag_key_spans[t][0] ||
                    memcmp(buf + ks, buf + tag_key_spans[t][0], ke - ks) != 0)
                    return -1;
            }
            tag_spans_out[(n_lines * max_tags + t) * 2] = vs;
            tag_spans_out[(n_lines * max_tags + t) * 2 + 1] = i;
            t++;
        }
        if (n_tags < 0) n_tags = t;
        else if (t != n_tags) return -1;
        if (i >= len || buf[i] != ' ') return -1;
        while (i < len && buf[i] == ' ') i++;
        // fields (floats only)
        int64_t f = 0;
        bool more = true;
        while (more) {
            int64_t ks = i;
            while (i < len && buf[i] != '=') {
                if (buf[i] == '\\' || buf[i] == ' ' || buf[i] == '\n') return -1;
                i++;
            }
            if (i >= len) return -1;
            int64_t ke = i;
            i++;
            if (i < len && buf[i] == '"') return -1;  // string field: fallback
            int64_t vs = i;
            while (i < len && buf[i] != ',' && buf[i] != ' ' && buf[i] != '\n') i++;
            if (i == vs) return -1;
            uint8_t last = buf[i - 1];
            if (last == 'i' || last == 'u' || last == 't' || last == 'T' ||
                last == 'e' || last == 'E') {
                // int/bool suffixes (or true/false): not the float shape
                // (exponents also bail — strtod below would handle them,
                // but 'e' is ambiguous with "false"; keep the fast path
                // strict and let the tokenizer path take the rest)
                return -1;
            }
            if (f >= max_fields || f >= 64) return -1;
            if (n_fields < 0) { field_key_spans[f][0] = ks; field_key_spans[f][1] = ke; }
            else {
                if (f >= n_fields) return -1;
                if (ke - ks != field_key_spans[f][1] - field_key_spans[f][0] ||
                    memcmp(buf + ks, buf + field_key_spans[f][0], ke - ks) != 0)
                    return -1;
            }
            char tmp[64];
            int64_t flen = i - vs;
            if (flen >= (int64_t)sizeof(tmp)) return -1;
            for (int64_t k = vs; k < i; k++)
                // strtod also eats hex floats ("0x1.8p3") and inf — the
                // exact (Python) path rejects those, so bail to it
                if (buf[k] == 'x' || buf[k] == 'X' || buf[k] == 'n' ||
                    buf[k] == 'N')
                    return -1;
            memcpy(tmp, buf + vs, flen);
            tmp[flen] = 0;
            char* endp = nullptr;
            double v = strtod(tmp, &endp);
            if (endp != tmp + flen) return -1;
            field_out[n_lines * max_fields + f] = v;
            f++;
            if (i < len && buf[i] == ',') { i++; continue; }
            more = false;
        }
        if (n_fields < 0) n_fields = f;
        else if (f != n_fields) return -1;
        // timestamp (required on the fast path)
        if (i >= len || buf[i] != ' ') return -1;
        while (i < len && buf[i] == ' ') i++;
        bool neg = false;
        if (i < len && buf[i] == '-') { neg = true; i++; }
        int64_t tv = 0;
        int ndig = 0;
        while (i < len && buf[i] >= '0' && buf[i] <= '9') {
            int d = buf[i] - '0';
            if (tv > (INT64_MAX - d) / 10) return -1;  // would overflow
            tv = tv * 10 + d;
            ndig++;
            i++;
        }
        if (ndig == 0) return -1;  // empty or a lone '-'
        if (i < len && buf[i] != '\n' && buf[i] != '\r' && buf[i] != ' ') return -1;
        if (neg) tv = -tv;
        if (mult_num > 1 &&
            (tv > INT64_MAX / mult_num || tv < INT64_MIN / mult_num))
            return -1;
        ts_out[n_lines] = tv * mult_num / mult_den;
        n_lines++;
        while (i < len && buf[i] != '\n') i++;
    }
    if (n_lines == 0 || n_tags < 0 || n_fields < 0) return -1;
    shape_out[0] = n_tags;
    shape_out[1] = n_fields;
    shape_out[2] = meas_s;
    shape_out[3] = meas_e;
    for (int64_t t = 0; t < n_tags; t++) {
        shape_out[4 + t * 2] = tag_key_spans[t][0];
        shape_out[4 + t * 2 + 1] = tag_key_spans[t][1];
    }
    for (int64_t f = 0; f < n_fields; f++) {
        shape_out[4 + max_tags * 2 + f * 2] = field_key_spans[f][0];
        shape_out[4 + max_tags * 2 + f * 2 + 1] = field_key_spans[f][1];
    }
    return n_lines;
}

// --------------------------------------------------------------- snappy ----
// Snappy block format (https://github.com/google/snappy/blob/main/format_description.txt),
// used by Prometheus remote write/read bodies (reference
// servers/src/http/prom_store.rs decodes the same format via the snap crate).

static int64_t read_varint(const uint8_t* in, int64_t len, int64_t* pos,
                           uint64_t* out_val) {
    uint64_t v = 0;
    int shift = 0;
    while (*pos < len && shift <= 63) {
        uint8_t b = in[(*pos)++];
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) { *out_val = v; return 0; }
        shift += 7;
    }
    return -1;
}

int64_t gt_snappy_uncompressed_length(const uint8_t* in, int64_t len) {
    int64_t pos = 0;
    uint64_t v;
    if (read_varint(in, len, &pos, &v) != 0) return -1;
    return (int64_t)v;
}

int64_t gt_snappy_decompress(const uint8_t* in, int64_t in_len,
                             uint8_t* out, int64_t out_cap) {
    int64_t ip = 0;
    uint64_t expect;
    if (read_varint(in, in_len, &ip, &expect) != 0) return -1;
    if ((int64_t)expect > out_cap) return -2;
    int64_t op = 0;
    while (ip < in_len) {
        uint8_t tag = in[ip++];
        uint32_t kind = tag & 3;
        if (kind == 0) {  // literal
            int64_t lit_len = (tag >> 2) + 1;
            if (lit_len > 60) {
                int extra = (int)lit_len - 60;  // 1..4 length bytes
                if (ip + extra > in_len) return -3;
                lit_len = 0;
                for (int k = 0; k < extra; k++) lit_len |= (int64_t)in[ip + k] << (8 * k);
                lit_len += 1;
                ip += extra;
            }
            if (ip + lit_len > in_len || op + lit_len > out_cap) return -3;
            memcpy(out + op, in + ip, lit_len);
            ip += lit_len;
            op += lit_len;
        } else {
            int64_t cp_len, offset;
            if (kind == 1) {
                if (ip >= in_len) return -3;
                cp_len = ((tag >> 2) & 7) + 4;
                offset = ((int64_t)(tag >> 5) << 8) | in[ip++];
            } else if (kind == 2) {
                if (ip + 2 > in_len) return -3;
                cp_len = (tag >> 2) + 1;
                offset = (int64_t)in[ip] | ((int64_t)in[ip + 1] << 8);
                ip += 2;
            } else {
                if (ip + 4 > in_len) return -3;
                cp_len = (tag >> 2) + 1;
                offset = (int64_t)in[ip] | ((int64_t)in[ip + 1] << 8) |
                         ((int64_t)in[ip + 2] << 16) | ((int64_t)in[ip + 3] << 24);
                ip += 4;
            }
            if (offset == 0 || offset > op || op + cp_len > out_cap) return -3;
            // Byte-at-a-time: copies may overlap their own output (RLE).
            for (int64_t k = 0; k < cp_len; k++) { out[op] = out[op - offset]; op++; }
        }
    }
    return op == (int64_t)expect ? op : -4;
}

static void write_varint(uint8_t* out, int64_t* op, uint64_t v) {
    while (v >= 0x80) { out[(*op)++] = (uint8_t)(v | 0x80); v >>= 7; }
    out[(*op)++] = (uint8_t)v;
}

static void emit_literal(const uint8_t* in, int64_t start, int64_t len,
                         uint8_t* out, int64_t* op) {
    int64_t n = len - 1;
    if (n < 60) {
        out[(*op)++] = (uint8_t)(n << 2);
    } else if (n < (1 << 8)) {
        out[(*op)++] = 60 << 2;
        out[(*op)++] = (uint8_t)n;
    } else if (n < (1 << 16)) {
        out[(*op)++] = 61 << 2;
        out[(*op)++] = (uint8_t)n;
        out[(*op)++] = (uint8_t)(n >> 8);
    } else if (n < (1 << 24)) {
        out[(*op)++] = 62 << 2;
        out[(*op)++] = (uint8_t)n;
        out[(*op)++] = (uint8_t)(n >> 8);
        out[(*op)++] = (uint8_t)(n >> 16);
    } else {
        out[(*op)++] = 63 << 2;
        out[(*op)++] = (uint8_t)n;
        out[(*op)++] = (uint8_t)(n >> 8);
        out[(*op)++] = (uint8_t)(n >> 16);
        out[(*op)++] = (uint8_t)(n >> 24);
    }
    memcpy(out + *op, in + start, len);
    *op += len;
}

static void emit_copy(int64_t offset, int64_t len, uint8_t* out, int64_t* op) {
    // Split long copies; snappy copy elements carry at most 64 bytes.
    while (len >= 68) {
        out[(*op)++] = (uint8_t)((63 << 2) | 2);
        out[(*op)++] = (uint8_t)offset;
        out[(*op)++] = (uint8_t)(offset >> 8);
        len -= 64;
    }
    if (len > 64) {  // leave >=4 for the tail element
        out[(*op)++] = (uint8_t)((59 << 2) | 2);
        out[(*op)++] = (uint8_t)offset;
        out[(*op)++] = (uint8_t)(offset >> 8);
        len -= 60;
    }
    if (len >= 4 && len <= 11 && offset < 2048) {
        out[(*op)++] = (uint8_t)(((offset >> 8) << 5) | ((len - 4) << 2) | 1);
        out[(*op)++] = (uint8_t)offset;
    } else {
        out[(*op)++] = (uint8_t)(((len - 1) << 2) | 2);
        out[(*op)++] = (uint8_t)offset;
        out[(*op)++] = (uint8_t)(offset >> 8);
    }
}

int64_t gt_snappy_max_compressed_length(int64_t n) {
    return 32 + n + n / 6;  // snappy's documented bound
}

int64_t gt_snappy_compress(const uint8_t* in, int64_t in_len,
                           uint8_t* out, int64_t out_cap) {
    if (out_cap < gt_snappy_max_compressed_length(in_len)) return -2;
    int64_t op = 0;
    write_varint(out, &op, (uint64_t)in_len);
    if (in_len == 0) return op;
    // Greedy LZ with a 16-bit hash of 4-byte windows (the classic snappy
    // scheme, one table per block).
    const int HASH_BITS = 14;
    static thread_local int64_t table[1 << 14];
    for (int64_t i = 0; i < (1 << HASH_BITS); i++) table[i] = -1;
    int64_t ip = 0, lit_start = 0;
    while (ip + 4 <= in_len) {
        uint32_t w;
        memcpy(&w, in + ip, 4);
        uint32_t h = (w * 0x1e35a7bdu) >> (32 - HASH_BITS);
        int64_t cand = table[h];
        table[h] = ip;
        uint32_t cw;
        if (cand >= 0 && ip - cand < 65536 &&
            (memcpy(&cw, in + cand, 4), cw == w)) {
            if (ip > lit_start) emit_literal(in, lit_start, ip - lit_start, out, &op);
            int64_t match = 4;
            while (ip + match < in_len && in[cand + match] == in[ip + match]) match++;
            emit_copy(ip - cand, match, out, &op);
            ip += match;
            lit_start = ip;
        } else {
            ip++;
        }
    }
    if (in_len > lit_start) emit_literal(in, lit_start, in_len - lit_start, out, &op);
    return op;
}

}  // extern "C"
