// Native runtime hot paths.
//
// The reference implements its whole runtime in Rust; these are the C++
// equivalents for the paths where Python overhead matters most:
//   - crc32 (zlib polynomial, slice-by-8): WAL frame checksums
//   - wal_scan: frame-walk a WAL buffer, validating lengths + CRCs and
//     reporting entry offsets (region open replays call this per region;
//     reference raft-engine does its recovery scan in native code too)
//   - lp_tokenize: InfluxDB line-protocol tokenizer emitting token offsets
//     (measurement/tag/field/timestamp spans) so Python only slices —
//     the ingest hot loop (reference servers/src/influxdb.rs + row_writer)
//
// Exposed with a plain C ABI for ctypes.  Build: `make` in native/.

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------- crc32 ----

static uint32_t crc_table[8][256];
static bool crc_init_done = false;

static void crc_init() {
    if (crc_init_done) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc_table[0][i];
        for (int s = 1; s < 8; s++) {
            c = crc_table[0][c & 0xFF] ^ (c >> 8);
            crc_table[s][i] = c;
        }
    }
    crc_init_done = true;
}

uint32_t gt_crc32(const uint8_t* data, size_t len, uint32_t seed) {
    crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    while (len >= 8) {
        c ^= (uint32_t)data[0] | ((uint32_t)data[1] << 8) |
             ((uint32_t)data[2] << 16) | ((uint32_t)data[3] << 24);
        uint32_t hi = (uint32_t)data[4] | ((uint32_t)data[5] << 8) |
                      ((uint32_t)data[6] << 16) | ((uint32_t)data[7] << 24);
        c = crc_table[7][c & 0xFF] ^ crc_table[6][(c >> 8) & 0xFF] ^
            crc_table[5][(c >> 16) & 0xFF] ^ crc_table[4][(c >> 24) & 0xFF] ^
            crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
            crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][(hi >> 24) & 0xFF];
        data += 8;
        len -= 8;
    }
    while (len--) c = crc_table[0][(c ^ *data++) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- wal scan ----

// Frame: [u32 payload_len][u32 crc32(payload)][u64 entry_id][payload]
// Scans up to max_entries frames; writes (offset, payload_len, entry_id)
// triples into out (3 * max_entries int64 slots).  Returns the number of
// valid frames; stops at a torn/corrupt tail like the Python replay().
int64_t gt_wal_scan(const uint8_t* buf, int64_t len, int64_t* out,
                    int64_t max_entries) {
    crc_init();
    int64_t pos = 0, n = 0;
    const int64_t header = 16;
    while (n < max_entries && pos + header <= len) {
        uint32_t payload_len, crc;
        uint64_t entry_id;
        memcpy(&payload_len, buf + pos, 4);
        memcpy(&crc, buf + pos + 4, 4);
        memcpy(&entry_id, buf + pos + 8, 8);
        if (pos + header + (int64_t)payload_len > len) break;  // torn tail
        if (gt_crc32(buf + pos + header, payload_len, 0) != crc) break;
        out[n * 3 + 0] = pos + header;
        out[n * 3 + 1] = (int64_t)payload_len;
        out[n * 3 + 2] = (int64_t)entry_id;
        pos += header + payload_len;
        n++;
    }
    return n;
}

// ---------------------------------------------------- line protocol -------

// Token kinds emitted by lp_tokenize.
enum TokKind : int32_t {
    TOK_MEASUREMENT = 0,
    TOK_TAG_KEY = 1,
    TOK_TAG_VAL = 2,
    TOK_FIELD_KEY = 3,
    TOK_FIELD_FLOAT = 4,
    TOK_FIELD_INT = 5,
    TOK_FIELD_STR = 6,
    TOK_FIELD_BOOL_T = 7,
    TOK_FIELD_BOOL_F = 8,
    TOK_TIMESTAMP = 9,
    TOK_LINE_END = 10,
    TOK_ERROR = 11,
};

// Tokenizes `buf` into (kind, start, end) triples written to out
// (3 * max_tokens int64 slots, kind stored as int64).  Handles escapes
// (\,  \space  \= inside identifiers) and double-quoted strings with \".
// Escaped spans keep their backslashes; Python unescapes only when a
// backslash was seen (flagged by kind += 100).
// Returns token count, or -(1+offset) on error.
int64_t gt_lp_tokenize(const uint8_t* buf, int64_t len, int64_t* out,
                       int64_t max_tokens) {
    int64_t n = 0;
    int64_t i = 0;
    auto emit = [&](int64_t kind, int64_t s, int64_t e) -> bool {
        if (n >= max_tokens) return false;
        out[n * 3] = kind; out[n * 3 + 1] = s; out[n * 3 + 2] = e;
        n++;
        return true;
    };
    while (i < len) {
        // skip blank lines / comments
        while (i < len && (buf[i] == '\n' || buf[i] == '\r')) i++;
        if (i >= len) break;
        if (buf[i] == '#') {
            while (i < len && buf[i] != '\n') i++;
            continue;
        }
        // measurement (to unescaped ',' or ' ')
        int64_t start = i;
        bool escaped = false;
        while (i < len && buf[i] != ',' && buf[i] != ' ' && buf[i] != '\n') {
            if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
            else i++;
        }
        if (i >= len || buf[i] == '\n') return -(1 + start);
        if (!emit(TOK_MEASUREMENT + (escaped ? 100 : 0), start, i)) return n;
        // tags
        while (i < len && buf[i] == ',') {
            i++;
            start = i; escaped = false;
            while (i < len && buf[i] != '=') {
                if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                else i++;
            }
            if (i >= len) return -(1 + start);
            if (!emit(TOK_TAG_KEY + (escaped ? 100 : 0), start, i)) return n;
            i++;  // '='
            start = i; escaped = false;
            while (i < len && buf[i] != ',' && buf[i] != ' ') {
                if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                else i++;
            }
            if (!emit(TOK_TAG_VAL + (escaped ? 100 : 0), start, i)) return n;
        }
        if (i >= len || buf[i] != ' ') return -(1 + i);
        while (i < len && buf[i] == ' ') i++;
        // fields
        bool more_fields = true;
        while (more_fields) {
            start = i; escaped = false;
            while (i < len && buf[i] != '=') {
                if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                else i++;
            }
            if (i >= len) return -(1 + start);
            if (!emit(TOK_FIELD_KEY + (escaped ? 100 : 0), start, i)) return n;
            i++;  // '='
            if (i < len && buf[i] == '"') {
                i++;
                start = i; escaped = false;
                while (i < len && buf[i] != '"') {
                    if (buf[i] == '\\' && i + 1 < len) { escaped = true; i += 2; }
                    else i++;
                }
                if (i >= len) return -(1 + start);
                if (!emit(TOK_FIELD_STR + (escaped ? 100 : 0), start, i)) return n;
                i++;  // closing quote
            } else {
                start = i;
                while (i < len && buf[i] != ',' && buf[i] != ' ' && buf[i] != '\n') i++;
                int64_t end = i;
                if (end == start) return -(1 + start);
                uint8_t last = buf[end - 1];
                int64_t kind;
                if (end - start == 1 && (buf[start] == 't' || buf[start] == 'T'))
                    kind = TOK_FIELD_BOOL_T;
                else if (end - start == 1 && (buf[start] == 'f' || buf[start] == 'F'))
                    kind = TOK_FIELD_BOOL_F;
                else if ((end - start == 4 && !strncmp((const char*)buf + start, "true", 4)))
                    kind = TOK_FIELD_BOOL_T;
                else if ((end - start == 5 && !strncmp((const char*)buf + start, "false", 5)))
                    kind = TOK_FIELD_BOOL_F;
                else if (last == 'i' || last == 'u')
                    kind = TOK_FIELD_INT;
                else
                    kind = TOK_FIELD_FLOAT;
                if (!emit(kind, start, end)) return n;
            }
            if (i < len && buf[i] == ',') { i++; continue; }
            more_fields = false;
        }
        // optional timestamp
        if (i < len && buf[i] == ' ') {
            while (i < len && buf[i] == ' ') i++;
            start = i;
            while (i < len && buf[i] != '\n' && buf[i] != ' ' && buf[i] != '\r') i++;
            if (i > start) {
                if (!emit(TOK_TIMESTAMP, start, i)) return n;
            }
        }
        if (!emit(TOK_LINE_END, i, i)) return n;
        while (i < len && buf[i] != '\n') i++;
    }
    return n;
}

}  // extern "C"
