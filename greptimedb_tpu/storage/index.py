"""Per-SST secondary indexes: bloom-filter skip index + inverted index.

Role-equivalent of the reference's `index` crate and
`mito2/src/sst/index/` (reference index/src/bloom_filter/,
index/src/inverted_index/, mito2/src/sst/index/indexer/): indexes are
built while an SST is written, stored in a Puffin sidecar, and consulted
at scan time to prune row groups / row segments before any Parquet decode.

Both indexes work at *segment* granularity (`segment_rows` rows per
segment, reference bloom_filter creator's `rows_per_segment`): an equality
or IN predicate on an indexed column yields a bitmap of candidate
segments; segments map to Parquet row groups for pruning, and the residual
filter still runs afterwards so index false positives are harmless.

TPU note: pruning happens host-side before tiles are staged to HBM — the
fewer segments survive, the fewer tiles the device sees; this is the
reference's "indexes shrink the scan" design carried over.
"""

from __future__ import annotations

import hashlib
import json
import struct
import zlib

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

BLOOM_BLOB = "greptime-bloom-filter-v1"
INVERTED_BLOB = "greptime-inverted-index-v1"
DEFAULT_SEGMENT_ROWS = 1024
BLOOM_FPP = 0.01


def _hash2(value: bytes) -> tuple[int, int]:
    d = hashlib.blake2b(value, digest_size=16).digest()
    h1, h2 = struct.unpack("<QQ", d)
    # h2 must be odd: nbits is often a power of two, and an even stride makes
    # the double-hash probe sequence cycle over a handful of positions,
    # destroying the false-positive guarantee.
    return h1, h2 | 1


class BloomFilter:
    """Split-bloom with double hashing (k probes from two 64-bit hashes)."""

    def __init__(self, nbits: int, k: int, bits: np.ndarray | None = None):
        self.nbits = nbits
        self.k = k
        self.bits = bits if bits is not None else np.zeros((nbits + 7) // 8, dtype=np.uint8)

    @classmethod
    def with_capacity(cls, n_items: int, fpp: float = BLOOM_FPP) -> "BloomFilter":
        n_items = max(n_items, 1)
        nbits = max(int(-n_items * np.log(fpp) / (np.log(2) ** 2)), 256)
        k = max(int(round(nbits / n_items * np.log(2))), 1)
        return cls(nbits, min(k, 16))

    def _positions(self, value: bytes) -> np.ndarray:
        h1, h2 = _hash2(value)
        i = np.arange(self.k, dtype=np.uint64)
        return ((h1 + i * h2) % np.uint64(self.nbits)).astype(np.int64)

    def add(self, value: bytes):
        p = self._positions(value)
        np.bitwise_or.at(self.bits, p >> 3, (1 << (p & 7)).astype(np.uint8))

    def contains(self, value: bytes) -> bool:
        p = self._positions(value)
        return bool(np.all(self.bits[p >> 3] & (1 << (p & 7)).astype(np.uint8)))

    def to_bytes(self) -> bytes:
        return struct.pack("<II", self.nbits, self.k) + self.bits.tobytes()

    @classmethod
    def from_bytes(cls, b: bytes) -> "BloomFilter":
        nbits, k = struct.unpack("<II", b[:8])
        return cls(nbits, k, np.frombuffer(b[8:], dtype=np.uint8).copy())


def _term_key(v) -> str | None:
    """Canonical string for a term so the SAME normalization applies at
    build and at search (a float literal 3.0 must find integer key 3)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        v = int(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return str(v)


def _encode_value(v) -> bytes:
    key = _term_key(v)
    if key is None:
        return b"\x00<null>"
    return key.encode()


# ---- build ------------------------------------------------------------------


def build_bloom_index(
    column: pa.Array, segment_rows: int = DEFAULT_SEGMENT_ROWS, fpp: float = BLOOM_FPP
) -> bytes:
    """One bloom filter per segment; blob = header json + concatenated filters
    (reference index/src/bloom_filter/creator.rs)."""
    n = len(column)
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = pc.cast(column, column.type.value_type)
    segs = []
    for start in range(0, n, segment_rows):
        seg = column.slice(start, segment_rows)
        distinct = pc.unique(seg)
        bf = BloomFilter.with_capacity(len(distinct), fpp)
        for v in distinct.to_pylist():
            bf.add(_encode_value(v))
        segs.append(bf.to_bytes())
    header = json.dumps(
        {"segment_rows": segment_rows, "n_rows": n, "seg_sizes": [len(s) for s in segs]}
    ).encode()
    return struct.pack("<I", len(header)) + header + b"".join(segs)


def build_inverted_index(
    column: pa.Array, segment_rows: int = DEFAULT_SEGMENT_ROWS, max_terms: int = 4096
) -> bytes | None:
    """term -> packed segment bitmap (reference index/src/inverted_index/
    format: FST + per-value bitmaps; here a sorted term table + bitmaps).

    Returns None when the column is too high-cardinality to index usefully.
    """
    n = len(column)
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = pc.cast(column, column.type.value_type)
    n_segs = (n + segment_rows - 1) // segment_rows
    d = pc.dictionary_encode(column)
    terms = d.dictionary.to_pylist()
    if len(terms) > max_terms:
        return None
    codes = np.asarray(pc.fill_null(pc.cast(d.indices, pa.int64()), len(terms)), dtype=np.int64)
    seg_ids = np.arange(n) // segment_rows
    # bitmap[term, seg]
    bm = np.zeros((len(terms) + 1, n_segs), dtype=bool)
    bm[codes, seg_ids] = True
    packed = np.packbits(bm, axis=1)
    payload = zlib.compress(packed.tobytes(), 3)
    header = json.dumps(
        {
            "segment_rows": segment_rows,
            "n_rows": n,
            "n_segs": n_segs,
            "terms": [_term_key(t) for t in terms],
            "row_bytes": packed.shape[1],
        }
    ).encode()
    return struct.pack("<I", len(header)) + header + payload


# ---- search -----------------------------------------------------------------


def _split_blob(blob: bytes) -> tuple[dict, bytes]:
    hlen = struct.unpack("<I", blob[:4])[0]
    header = json.loads(blob[4 : 4 + hlen])
    return header, blob[4 + hlen :]


class BloomIndex:
    """Parsed per-segment bloom filters (decode once, search many times)."""

    def __init__(self, blob: bytes):
        header, body = _split_blob(blob)
        self.segment_rows = header["segment_rows"]
        self.filters: list[BloomFilter] = []
        off = 0
        for sz in header["seg_sizes"]:
            self.filters.append(BloomFilter.from_bytes(body[off : off + sz]))
            off += sz

    def search(self, op: str, value) -> np.ndarray | None:
        """Segment candidacy bitmap for `col op value`; None = can't prune."""
        if op not in ("=", "in"):
            return None
        values = [_encode_value(v) for v in (value if op == "in" else [value])]
        out = np.zeros(len(self.filters), dtype=bool)
        for i, bf in enumerate(self.filters):
            out[i] = any(bf.contains(v) for v in values)
        return out


class InvertedIndex:
    """Parsed term -> segment-bitmap table (decode once, search many times)."""

    def __init__(self, blob: bytes):
        header, payload = _split_blob(blob)
        self.segment_rows = header["segment_rows"]
        self.terms: list[str | None] = header["terms"]
        self.n_segs = header["n_segs"]
        packed = np.frombuffer(zlib.decompress(payload), dtype=np.uint8).reshape(
            -1, header["row_bytes"]
        )
        self.bm = np.unpackbits(packed, axis=1)[:, : self.n_segs].astype(bool)
        self._term_idx = {t: i for i, t in enumerate(self.terms)}

    def _term_rows(self, v) -> np.ndarray:
        i = self._term_idx.get(_term_key(v))
        if i is None:
            return np.zeros(self.n_segs, dtype=bool)
        return self.bm[i]

    def search(self, op: str, value) -> np.ndarray | None:
        """Segment bitmap; supports =, in, != (exact, no false positives)."""
        if op == "=":
            return self._term_rows(value)
        if op == "in":
            out = np.zeros(self.n_segs, dtype=bool)
            for v in value:
                out |= self._term_rows(v)
            return out
        if op == "!=":
            # segments containing at least one row of any OTHER term
            # (NULL rows never match != under SQL three-valued logic)
            out = np.zeros(self.n_segs, dtype=bool)
            key = _term_key(value)
            for i, t in enumerate(self.terms):
                if t != key:
                    out |= self.bm[i]
            return out
        return None


def search_bloom_index(blob: bytes, op: str, value) -> np.ndarray | None:
    return BloomIndex(blob).search(op, value)


def search_inverted_index(blob: bytes, op: str, value) -> np.ndarray | None:
    return InvertedIndex(blob).search(op, value)


class IndexCache:
    """Tiny LRU for parsed puffin sidecars (reference mito2/src/cache/index/)."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._data: dict[str, dict] = {}

    def get(self, key: str):
        v = self._data.pop(key, None)
        if v is not None:
            self._data[key] = v
        return v

    def put(self, key: str, value):
        if key in self._data:
            self._data.pop(key)
        elif len(self._data) >= self.capacity:
            self._data.pop(next(iter(self._data)))
        self._data[key] = value
