"""Per-SST secondary indexes: bloom-filter skip index + inverted index.

Role-equivalent of the reference's `index` crate and
`mito2/src/sst/index/` (reference index/src/bloom_filter/,
index/src/inverted_index/, mito2/src/sst/index/indexer/): indexes are
built while an SST is written, stored in a Puffin sidecar, and consulted
at scan time to prune row groups / row segments before any Parquet decode.

Both indexes work at *segment* granularity (`segment_rows` rows per
segment, reference bloom_filter creator's `rows_per_segment`): an equality
or IN predicate on an indexed column yields a bitmap of candidate
segments; segments map to Parquet row groups for pruning, and the residual
filter still runs afterwards so index false positives are harmless.

TPU note: pruning happens host-side before tiles are staged to HBM — the
fewer segments survive, the fewer tiles the device sees; this is the
reference's "indexes shrink the scan" design carried over.
"""

from __future__ import annotations

import hashlib
import json
import struct
import zlib

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

BLOOM_BLOB = "greptime-bloom-filter-v1"
INVERTED_BLOB = "greptime-inverted-index-v1"
FULLTEXT_BLOB = "greptime-fulltext-index-v1"
VECTOR_BLOB = "greptime-vector-index-v1"
DEFAULT_SEGMENT_ROWS = 1024
BLOOM_FPP = 0.01


def _hash2(value: bytes) -> tuple[int, int]:
    d = hashlib.blake2b(value, digest_size=16).digest()
    h1, h2 = struct.unpack("<QQ", d)
    # h2 must be odd: nbits is often a power of two, and an even stride makes
    # the double-hash probe sequence cycle over a handful of positions,
    # destroying the false-positive guarantee.
    return h1, h2 | 1


class BloomFilter:
    """Split-bloom with double hashing (k probes from two 64-bit hashes)."""

    def __init__(self, nbits: int, k: int, bits: np.ndarray | None = None):
        self.nbits = nbits
        self.k = k
        self.bits = bits if bits is not None else np.zeros((nbits + 7) // 8, dtype=np.uint8)

    @classmethod
    def with_capacity(cls, n_items: int, fpp: float = BLOOM_FPP) -> "BloomFilter":
        n_items = max(n_items, 1)
        nbits = max(int(-n_items * np.log(fpp) / (np.log(2) ** 2)), 256)
        k = max(int(round(nbits / n_items * np.log(2))), 1)
        return cls(nbits, min(k, 16))

    def _positions(self, value: bytes) -> np.ndarray:
        h1, h2 = _hash2(value)
        i = np.arange(self.k, dtype=np.uint64)
        return ((h1 + i * h2) % np.uint64(self.nbits)).astype(np.int64)

    def add(self, value: bytes):
        p = self._positions(value)
        np.bitwise_or.at(self.bits, p >> 3, (1 << (p & 7)).astype(np.uint8))

    def contains(self, value: bytes) -> bool:
        p = self._positions(value)
        return bool(np.all(self.bits[p >> 3] & (1 << (p & 7)).astype(np.uint8)))

    def to_bytes(self) -> bytes:
        return struct.pack("<II", self.nbits, self.k) + self.bits.tobytes()

    @classmethod
    def from_bytes(cls, b: bytes) -> "BloomFilter":
        nbits, k = struct.unpack("<II", b[:8])
        return cls(nbits, k, np.frombuffer(b[8:], dtype=np.uint8).copy())


def _term_key(v) -> str | None:
    """Canonical string for a term so the SAME normalization applies at
    build and at search (a float literal 3.0 must find integer key 3)."""
    if v is None:
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        v = int(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return str(v)


def _encode_value(v) -> bytes:
    key = _term_key(v)
    if key is None:
        return b"\x00<null>"
    return key.encode()


# ---- build ------------------------------------------------------------------


def build_bloom_index(
    column: pa.Array, segment_rows: int = DEFAULT_SEGMENT_ROWS, fpp: float = BLOOM_FPP
) -> bytes:
    """One bloom filter per segment; blob = header json + concatenated filters
    (reference index/src/bloom_filter/creator.rs)."""
    n = len(column)
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = pc.cast(column, column.type.value_type)
    segs = []
    for start in range(0, n, segment_rows):
        seg = column.slice(start, segment_rows)
        distinct = pc.unique(seg)
        bf = BloomFilter.with_capacity(len(distinct), fpp)
        for v in distinct.to_pylist():
            bf.add(_encode_value(v))
        segs.append(bf.to_bytes())
    header = json.dumps(
        {"segment_rows": segment_rows, "n_rows": n, "seg_sizes": [len(s) for s in segs]}
    ).encode()
    return struct.pack("<I", len(header)) + header + b"".join(segs)


def build_inverted_index(
    column: pa.Array, segment_rows: int = DEFAULT_SEGMENT_ROWS, max_terms: int = 4096
) -> bytes | None:
    """term -> packed segment bitmap (reference index/src/inverted_index/
    format: FST + per-value bitmaps; here a sorted term table + bitmaps).

    Returns None when the column is too high-cardinality to index usefully.
    """
    n = len(column)
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = pc.cast(column, column.type.value_type)
    n_segs = (n + segment_rows - 1) // segment_rows
    d = pc.dictionary_encode(column)
    terms = d.dictionary.to_pylist()
    if len(terms) > max_terms:
        return None
    codes = np.asarray(pc.fill_null(pc.cast(d.indices, pa.int64()), len(terms)), dtype=np.int64)
    seg_ids = np.arange(n) // segment_rows
    # bitmap[term, seg]
    bm = np.zeros((len(terms) + 1, n_segs), dtype=bool)
    bm[codes, seg_ids] = True
    packed = np.packbits(bm, axis=1)
    payload = zlib.compress(packed.tobytes(), 3)
    header = json.dumps(
        {
            "segment_rows": segment_rows,
            "n_rows": n,
            "n_segs": n_segs,
            "terms": [_term_key(t) for t in terms],
            "row_bytes": packed.shape[1],
        }
    ).encode()
    return struct.pack("<I", len(header)) + header + payload


# ---- fulltext ---------------------------------------------------------------

import re as _re

_TOKEN_RE = _re.compile(r"[a-z0-9_]+")


def tokenize(text: str) -> list[str]:
    """Lowercase word tokenizer (the reference's default English analyzer
    shape: tantivy SimpleTokenizer + lowercase, index/src/fulltext_index/)."""
    return _TOKEN_RE.findall(text.lower())


def build_fulltext_index(
    column: pa.Array,
    segment_rows: int = DEFAULT_SEGMENT_ROWS,
    max_tokens: int = 1 << 16,
) -> bytes | None:
    """token -> segment bitmap over a tokenized text column (reference
    mito2/src/sst/index/fulltext_index/ creator; segment-granular like the
    bloom/inverted indexes so pruning plugs into the same applier).  None
    when the vocabulary exceeds `max_tokens` (index would not pay off)."""
    n = len(column)
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = pc.cast(column, column.type.value_type)
    n_segs = (n + segment_rows - 1) // segment_rows
    vocab: dict[str, int] = {}
    rows_tok: list[set] = []
    for i, v in enumerate(column.to_pylist()):
        if v is None:
            continue
        seg = i // segment_rows
        while len(rows_tok) <= seg:
            rows_tok.append(set())
        for t in tokenize(str(v)):
            code = vocab.setdefault(t, len(vocab))
            rows_tok[seg].add(code)
        if len(vocab) > max_tokens:
            return None
    while len(rows_tok) < n_segs:
        rows_tok.append(set())
    bm = np.zeros((len(vocab), n_segs), dtype=bool)
    for seg, codes in enumerate(rows_tok):
        for c in codes:
            bm[c, seg] = True
    packed = np.packbits(bm, axis=1) if len(vocab) else np.zeros((0, 1), np.uint8)
    payload = zlib.compress(packed.tobytes(), 3)
    header = json.dumps(
        {
            "segment_rows": segment_rows,
            "n_rows": n,
            "n_segs": n_segs,
            "tokens": sorted(vocab, key=vocab.get),
            "row_bytes": int(packed.shape[1]) if len(vocab) else 1,
        }
    ).encode()
    return struct.pack("<I", len(header)) + header + payload


def parse_match_query(query: str) -> list[tuple[list[str], list[str], list[str]]]:
    """`matches()` query -> disjuncts of (AND terms, AND phrases, NOT terms).

    Grammar subset of the reference's matches() language: whitespace terms
    are ANDed, `OR` splits alternatives, `"quoted phrases"` must appear
    verbatim (case-insensitive), `-term` negates."""
    disjuncts: list[tuple[list[str], list[str], list[str]]] = []
    for part in _re.split(r"\s+OR\s+", query.strip()):
        terms: list[str] = []
        phrases: list[str] = []
        negs: list[str] = []
        for m in _re.finditer(r'"([^"]*)"|(\S+)', part):
            if m.group(1) is not None:
                phrases.append(m.group(1))
            else:
                tok = m.group(2)
                if tok.startswith("-") and len(tok) > 1:
                    negs.extend(tokenize(tok[1:]))
                else:
                    terms.extend(tokenize(tok))
        disjuncts.append((terms, phrases, negs))
    return disjuncts


class FulltextIndex:
    """Parsed token -> segment-bitmap table."""

    def __init__(self, blob: bytes):
        header, payload = _split_blob(blob)
        self.segment_rows = header["segment_rows"]
        self.tokens: list[str] = header["tokens"]
        self.n_segs = header["n_segs"]
        if self.tokens:
            packed = np.frombuffer(zlib.decompress(payload), dtype=np.uint8).reshape(
                -1, header["row_bytes"]
            )
            self.bm = np.unpackbits(packed, axis=1)[:, : self.n_segs].astype(bool)
        else:
            self.bm = np.zeros((0, self.n_segs), dtype=bool)
        self._tok_idx = {t: i for i, t in enumerate(self.tokens)}

    def _token_segs(self, token: str) -> np.ndarray:
        i = self._tok_idx.get(token.lower())
        if i is None:
            return np.zeros(self.n_segs, dtype=bool)
        return self.bm[i]

    def _substr_token_segs(self, token: str) -> np.ndarray:
        """Segments whose vocabulary contains `token` as a SUBSTRING of any
        stored token.  Phrase row-matching is substring-based
        (matches_mask uses pc.match_substring), so phrase pruning must be
        substring-conservative: '\"err\"' must keep segments holding
        'error'.  A phrase token is pure word chars, so it can only occur
        inside a single text token — the OR over containing vocab tokens
        is exact segment candidacy."""
        t = token.lower()
        out = np.zeros(self.n_segs, dtype=bool)
        for v, i in self._tok_idx.items():
            if t in v:
                out |= self.bm[i]
        return out

    def search(self, op: str, value) -> np.ndarray | None:
        """Conservative segment candidacy for match predicates: a segment
        survives when it MIGHT match (phrases fall back to their tokens;
        negations cannot prune)."""
        if op == "match_term":
            # the term may tokenize into several vocab tokens ('foo-bar' ->
            # foo, bar): AND their bitmaps (conservative); an un-tokenizable
            # term cannot prune at all
            toks = tokenize(str(value))
            if not toks:
                return None
            out = np.ones(self.n_segs, dtype=bool)
            for t in toks:
                out &= self._token_segs(t)
            return out
        if op != "match":
            return None
        out = np.zeros(self.n_segs, dtype=bool)
        for terms, phrases, _negs in parse_match_query(str(value)):
            cand = np.ones(self.n_segs, dtype=bool)
            for t in terms:
                cand &= self._token_segs(t)
            for p in phrases:
                for t in tokenize(p):
                    cand &= self._substr_token_segs(t)
            out |= cand
        return out


# word-boundary regex: equals the tokenizer's word split for [a-z0-9_] terms
def _term_regex(term: str) -> str:
    return r"(?i)(?:^|[^A-Za-z0-9_])" + _re.escape(term) + r"(?:[^A-Za-z0-9_]|$)"


def matches_term_mask(col, term) -> pa.Array:
    """Exact per-row matches_term predicate (reference matches_term UDF)."""
    return pc.match_substring_regex(col, _term_regex(str(term)))


def matches_mask(col, query) -> pa.Array:
    """Exact per-row matches() predicate over the parsed query language."""
    result = None
    for terms, phrases, negs in parse_match_query(str(query)):
        cand = None
        for t in terms:
            m = matches_term_mask(col, t)
            cand = m if cand is None else pc.and_kleene(cand, m)
        for p in phrases:
            m = pc.match_substring(col, p, ignore_case=True)
            cand = m if cand is None else pc.and_kleene(cand, m)
        for t in negs:
            m = pc.invert(matches_term_mask(col, t))
            cand = m if cand is None else pc.and_kleene(cand, m)
        if cand is None:
            continue
        result = cand if result is None else pc.or_kleene(result, cand)
    if result is None:
        import numpy as _np

        return pa.array(_np.ones(len(col), dtype=bool))
    return result


# ---- search -----------------------------------------------------------------


def _split_blob(blob: bytes) -> tuple[dict, bytes]:
    hlen = struct.unpack("<I", blob[:4])[0]
    header = json.loads(blob[4 : 4 + hlen])
    return header, blob[4 + hlen :]


class BloomIndex:
    """Parsed per-segment bloom filters (decode once, search many times)."""

    def __init__(self, blob: bytes):
        header, body = _split_blob(blob)
        self.segment_rows = header["segment_rows"]
        self.filters: list[BloomFilter] = []
        off = 0
        for sz in header["seg_sizes"]:
            self.filters.append(BloomFilter.from_bytes(body[off : off + sz]))
            off += sz

    def search(self, op: str, value) -> np.ndarray | None:
        """Segment candidacy bitmap for `col op value`; None = can't prune."""
        if op not in ("=", "in"):
            return None
        values = [_encode_value(v) for v in (value if op == "in" else [value])]
        out = np.zeros(len(self.filters), dtype=bool)
        for i, bf in enumerate(self.filters):
            out[i] = any(bf.contains(v) for v in values)
        return out


class InvertedIndex:
    """Parsed term -> segment-bitmap table (decode once, search many times)."""

    def __init__(self, blob: bytes):
        header, payload = _split_blob(blob)
        self.segment_rows = header["segment_rows"]
        self.terms: list[str | None] = header["terms"]
        self.n_segs = header["n_segs"]
        packed = np.frombuffer(zlib.decompress(payload), dtype=np.uint8).reshape(
            -1, header["row_bytes"]
        )
        self.bm = np.unpackbits(packed, axis=1)[:, : self.n_segs].astype(bool)
        self._term_idx = {t: i for i, t in enumerate(self.terms)}

    def _term_rows(self, v) -> np.ndarray:
        i = self._term_idx.get(_term_key(v))
        if i is None:
            return np.zeros(self.n_segs, dtype=bool)
        return self.bm[i]

    def search(self, op: str, value) -> np.ndarray | None:
        """Segment bitmap; supports =, in, != (exact, no false positives)."""
        if op == "=":
            return self._term_rows(value)
        if op == "in":
            out = np.zeros(self.n_segs, dtype=bool)
            for v in value:
                out |= self._term_rows(v)
            return out
        if op == "!=":
            # segments containing at least one row of any OTHER term
            # (NULL rows never match != under SQL three-valued logic)
            out = np.zeros(self.n_segs, dtype=bool)
            key = _term_key(value)
            for i, t in enumerate(self.terms):
                if t != key:
                    out |= self.bm[i]
            return out
        return None


def search_bloom_index(blob: bytes, op: str, value) -> np.ndarray | None:
    return BloomIndex(blob).search(op, value)


def search_inverted_index(blob: bytes, op: str, value) -> np.ndarray | None:
    return InvertedIndex(blob).search(op, value)


class IndexCache:
    """Tiny LRU for parsed puffin sidecars (reference mito2/src/cache/index/)."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._data: dict[str, dict] = {}

    def get(self, key: str):
        v = self._data.pop(key, None)
        if v is not None:
            self._data[key] = v
        return v

    def put(self, key: str, value):
        if key in self._data:
            self._data.pop(key)
        elif len(self._data) >= self.capacity:
            self._data.pop(next(iter(self._data)))
        self._data[key] = value


# ---- vector (ANN) index -----------------------------------------------------
# IVF-flat per SST (reference mito2/src/sst/index/vector_index/, which wraps
# usearch HNSW — IVF-flat is the TPU-friendly choice: probing is a batched
# centroid matmul, re-ranking a candidate matmul, both MXU shapes).


def build_vector_index(column: pa.Array, dim: int) -> bytes | None:
    """Binary-f32 vector column -> serialized IVF-flat index (coarse
    centroids + per-row assignments).  None for empty columns."""
    from ..query.vector import build_ivf, decode_matrix

    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    mat, valid = decode_matrix(column, dim)
    if not valid.any():
        return None
    cent, assign = build_ivf(mat, valid)
    header = json.dumps(
        {"dim": dim, "nlist": len(cent), "n": len(assign)}
    ).encode()
    payload = zlib.compress(cent.astype("<f4").tobytes() + assign.astype("<i4").tobytes())
    return struct.pack("<I", len(header)) + header + payload


class VectorIndex:
    """Parsed IVF-flat blob: probe nprobe nearest cells -> candidate rows."""

    def __init__(self, blob: bytes):
        header, payload = _split_blob(blob)
        self.dim = header["dim"]
        self.nlist = header["nlist"]
        self.n = header["n"]
        raw = zlib.decompress(payload)
        cbytes = self.nlist * self.dim * 4
        self.centroids = np.frombuffer(raw[:cbytes], dtype="<f4").reshape(
            self.nlist, self.dim
        )
        self.assign = np.frombuffer(raw[cbytes:], dtype="<i4")

    def candidates(self, q: np.ndarray, nprobe: int = 4) -> np.ndarray:
        from ..query.vector import ivf_candidates

        return ivf_candidates(self.centroids, self.assign, q, nprobe)
