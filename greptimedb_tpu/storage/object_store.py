"""Object-store abstraction under SSTs, puffin sidecars, and manifests.

Role-equivalent of the reference's `object-store` crate (reference
src/object-store/src/lib.rs:16-20 — a thin wrapper over OpenDAL with
fs/s3/gcs/oss/azblob builders, retry + metrics + LRU-cache layers, and an
`ObjectStoreManager` for per-table storage selection).  The TPU build keeps
the same shape: a small `ObjectStore` interface with composable layers, an
always-available `fs` backend, a `memory` backend for tests, and the remote
backends surfaced in config but gated (this build runs with zero egress).

The WAL deliberately does NOT go through this layer: like the reference's
raft-engine log store, it is a local-disk append log (reference
src/log-store/src/raft_engine/log_store.rs:42).

Keys are forward-slash relative paths ("region_7/sst/abc.parquet").
`open_input` bridges to pyarrow: the fs backend hands back a real filesystem
path (mmap-friendly for parquet), others a `pa.BufferReader`.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict

import pyarrow as pa

from ..utils import fault_injection, metrics
from ..utils.errors import ConfigError

OBJECT_STORE_READS = metrics.Counter("object_store_reads", "object store read ops")
OBJECT_STORE_WRITES = metrics.Counter("object_store_writes", "object store write ops")
OBJECT_STORE_CACHE_HITS = metrics.Counter(
    "object_store_cache_hits", "reads served from the LRU object cache"
)


class ObjectStore:
    """Minimal blob-store interface (reference `ObjectStore` = opendal::Operator)."""

    def read(self, key: str) -> bytes:
        raise NotImplementedError

    def read_range(self, key: str, offset: int, length: int) -> bytes:
        """Ranged read of `length` bytes at `offset` (reference: opendal
        `read_with(..).range(..)`; S3/GCS range GETs).  The segmented term
        index depends on this being O(length), not O(object): backends
        with seekable storage override it — this default exists so exotic
        layers stay correct, not fast."""
        return self.read(key)[offset : offset + length]

    def write(self, key: str, data: bytes) -> None:
        """Atomic full-object write."""
        raise NotImplementedError

    def put_file(self, key: str, local_src: str) -> None:
        """Ingest a locally-written file (moves when possible)."""
        with open(local_src, "rb") as f:
            self.write(key, f.read())
        os.remove(local_src)

    def open_input(self, key: str):
        """Something pyarrow can read: a filesystem path str or BufferReader."""
        return pa.BufferReader(self.read(key))

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        """Keys under prefix (non-recursive names, like a directory listing)."""
        raise NotImplementedError

    def size(self, key: str) -> int:
        return len(self.read(key))

    def scoped(self, prefix: str) -> "ObjectStore":
        """A view of this store under `prefix` (reference's chroot layer)."""
        return PrefixStore(self, prefix)

    def scratch_path(self, key: str) -> str:
        """A local path a writer may produce the object at before put_file.
        Backends with a real directory return a sibling tmp path so
        put_file can be a rename; others return a tmp-dir path."""
        import tempfile

        return os.path.join(tempfile.gettempdir(), f"gtpu-{os.getpid()}-{key.replace('/', '_')}")

    def purge_incomplete(self, prefix: str = "") -> None:
        """Remove leftovers of writes that crashed mid-flight (fs .tmp
        files).  No-op for backends whose writes are naturally atomic."""


class FsObjectStore(ObjectStore):
    """Local-filesystem backend; writes are tmp+rename atomic."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    def read(self, key: str) -> bytes:
        OBJECT_STORE_READS.inc()
        with open(self._p(key), "rb") as f:
            return f.read()

    def read_range(self, key: str, offset: int, length: int) -> bytes:
        OBJECT_STORE_READS.inc()
        with open(self._p(key), "rb") as f:
            f.seek(offset)
            return f.read(length)

    def write(self, key: str, data: bytes) -> None:
        OBJECT_STORE_WRITES.inc()
        path = self._p(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def put_file(self, key: str, local_src: str) -> None:
        OBJECT_STORE_WRITES.inc()
        path = self._p(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        os.replace(local_src, path)

    def open_input(self, key: str):
        OBJECT_STORE_READS.inc()
        return self._p(key)

    def exists(self, key: str) -> bool:
        return os.path.exists(self._p(key))

    def delete(self, key: str) -> None:
        try:
            os.remove(self._p(key))
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> list[str]:
        d = self._p(prefix) if prefix else self.root
        if not os.path.isdir(d):
            return []
        return [n for n in os.listdir(d) if not n.endswith(".tmp")]

    def size(self, key: str) -> int:
        return os.path.getsize(self._p(key))

    def scratch_path(self, key: str) -> str:
        path = self._p(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        return path + ".scratch"

    def purge_incomplete(self, prefix: str = "") -> None:
        d = self._p(prefix) if prefix else self.root
        if not os.path.isdir(d):
            return
        for name in os.listdir(d):
            if name.endswith((".tmp", ".scratch")):
                try:
                    os.remove(os.path.join(d, name))
                except FileNotFoundError:
                    pass


class MemoryObjectStore(ObjectStore):
    """Dict-backed store for tests (reference uses memory backends likewise)."""

    def __init__(self):
        self._objects: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def read(self, key: str) -> bytes:
        OBJECT_STORE_READS.inc()
        with self._lock:
            if key not in self._objects:
                raise FileNotFoundError(key)
            return self._objects[key]

    def write(self, key: str, data: bytes) -> None:
        OBJECT_STORE_WRITES.inc()
        with self._lock:
            self._objects[key] = bytes(data)

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._objects

    def delete(self, key: str) -> None:
        with self._lock:
            self._objects.pop(key, None)

    def list(self, prefix: str = "") -> list[str]:
        pre = prefix.rstrip("/") + "/" if prefix else ""
        with self._lock:
            out = set()
            for k in self._objects:
                if k.startswith(pre):
                    out.add(k[len(pre) :].split("/", 1)[0])
            return sorted(out)

    def size(self, key: str) -> int:
        with self._lock:
            if key not in self._objects:
                raise FileNotFoundError(key)
            return len(self._objects[key])


class SimulatedRemoteStore(ObjectStore):
    """Remote-backend stand-in (reference object-store/src/factory.rs
    builds s3/gcs/oss/azblob here; this build has no network, so a
    directory plays the bucket).  Behaves like a remote for the layer
    stack: every operation pays injected latency, a configurable fraction
    of operations fail transiently with ConnectionError-grade OSErrors
    (exercising RetryLayer), put_file UPLOADS bytes instead of renaming,
    and there is no local scratch sibling.  `op_counts` lets tests assert
    which operations actually crossed the "network" — the whole point is
    proving the retry/write-cache/LRU layers off-load it."""

    def __init__(self, root: str, latency_ms: float = 0.0, fail_every: int = 0):
        self._backing = FsObjectStore(root)
        self.latency_ms = latency_ms
        self.fail_every = fail_every  # every Nth mutating/read op fails once
        self._op_seq = 0
        self.op_counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def _network(self, op: str):
        with self._lock:
            self._op_seq += 1
            self.op_counts[op] = self.op_counts.get(op, 0) + 1
            fail = self.fail_every and self._op_seq % self.fail_every == 0
        if self.latency_ms:
            time.sleep(self.latency_ms / 1000.0)
        if fail:
            raise TimeoutError(f"simulated remote timeout during {op}")

    def read(self, key):
        self._network("read")
        return self._backing.read(key)

    def read_range(self, key, offset, length):
        # one network round per range GET, like a real remote store
        self._network("read_range")
        return self._backing.read_range(key, offset, length)

    def write(self, key, data):
        self._network("write")
        self._backing.write(key, data)

    def put_file(self, key, local_src):
        # a REAL upload: bytes move over the simulated network, then the
        # local file goes away (no rename fast path on remote stores)
        self._network("put")
        with open(local_src, "rb") as f:
            self._backing.write(key, f.read())
        os.remove(local_src)

    def exists(self, key):
        self._network("exists")
        return self._backing.exists(key)

    def delete(self, key):
        self._network("delete")
        self._backing.delete(key)

    def list(self, prefix=""):
        self._network("list")
        return self._backing.list(prefix)

    def size(self, key):
        self._network("size")
        return self._backing.size(key)

    def purge_incomplete(self, prefix=""):
        self._backing.purge_incomplete(prefix)


class PrefixStore(ObjectStore):
    """Chroot view: all keys are joined under a fixed prefix."""

    def __init__(self, inner: ObjectStore, prefix: str):
        self.inner = inner
        self.prefix = prefix.strip("/")

    def _k(self, key: str) -> str:
        return f"{self.prefix}/{key}" if key else self.prefix

    def read(self, key):
        return self.inner.read(self._k(key))

    def read_range(self, key, offset, length):
        return self.inner.read_range(self._k(key), offset, length)

    def write(self, key, data):
        self.inner.write(self._k(key), data)

    def put_file(self, key, local_src):
        self.inner.put_file(self._k(key), local_src)

    def open_input(self, key):
        return self.inner.open_input(self._k(key))

    def exists(self, key):
        return self.inner.exists(self._k(key))

    def delete(self, key):
        self.inner.delete(self._k(key))

    def list(self, prefix=""):
        return self.inner.list(self._k(prefix) if prefix else self.prefix)

    def size(self, key):
        return self.inner.size(self._k(key))

    def scratch_path(self, key):
        return self.inner.scratch_path(self._k(key))

    def purge_incomplete(self, prefix=""):
        self.inner.purge_incomplete(self._k(prefix) if prefix else self.prefix)


class RetryLayer(ObjectStore):
    """Retry transient IO errors with exponential backoff (reference wraps
    every store in opendal's RetryLayer).  Backoff/classification live in
    the repo-wide `utils/retry.py` policy — this layer only names the
    fault-injection point each operation fires under, so chaos tests can
    make the backing store flaky and watch the retries absorb it."""

    def __init__(self, inner: ObjectStore, attempts: int = 3, base_delay_s: float = 0.05):
        from ..utils.retry import RetryPolicy, is_transient_io

        self.inner = inner
        self.policy = RetryPolicy(
            # 0/negative attempts would mean "never even try"
            max_attempts=max(1, attempts),
            base_delay_s=base_delay_s,
            classify=is_transient_io,
        )

    def _retry(self, point, fn, *args):
        def attempt():
            fault_injection.fire(point)
            return fn(*args)

        return self.policy.call(attempt)

    def read(self, key):
        return self._retry("store.read", self.inner.read, key)

    def read_range(self, key, offset, length):
        return self._retry("store.read", self.inner.read_range, key, offset, length)

    def write(self, key, data):
        return self._retry("store.write", self.inner.write, key, data)

    def put_file(self, key, local_src):
        return self._retry("store.write", self.inner.put_file, key, local_src)

    def open_input(self, key):
        return self._retry("store.read", self.inner.open_input, key)

    def exists(self, key):
        return self.inner.exists(key)

    def delete(self, key):
        return self._retry("store.write", self.inner.delete, key)

    def list(self, prefix=""):
        return self._retry("store.read", self.inner.list, prefix)

    def size(self, key):
        return self._retry("store.read", self.inner.size, key)

    def scratch_path(self, key):
        return self.inner.scratch_path(key)

    def purge_incomplete(self, prefix=""):
        self.inner.purge_incomplete(prefix)


class LruCacheLayer(ObjectStore):
    """Byte-LRU over whole-object reads (reference's LRU object cache layer,
    `OBJECT_CACHE_DIR`).  Caches read()/open_input() payloads; writes and
    deletes invalidate.  list()/exists() always pass through."""

    def __init__(self, inner: ObjectStore, capacity_bytes: int = 64 << 20):
        self.inner = inner
        self.capacity = capacity_bytes
        self._cache: OrderedDict[str, bytes] = OrderedDict()
        self._used = 0
        self._lock = threading.Lock()

    def _put(self, key: str, data: bytes):
        if len(data) > self.capacity:
            return
        with self._lock:
            old = self._cache.pop(key, None)
            if old is not None:
                self._used -= len(old)
            self._cache[key] = data
            self._used += len(data)
            while self._used > self.capacity:
                _, evicted = self._cache.popitem(last=False)
                self._used -= len(evicted)

    def _invalidate(self, key: str):
        with self._lock:
            old = self._cache.pop(key, None)
            if old is not None:
                self._used -= len(old)

    def read(self, key):
        with self._lock:
            data = self._cache.get(key)
            if data is not None:
                self._cache.move_to_end(key)
        if data is not None:
            OBJECT_STORE_CACHE_HITS.inc()
            return data
        data = self.inner.read(key)
        self._put(key, data)
        return data

    def read_range(self, key, offset, length):
        # a cached whole object answers the range locally; otherwise pass
        # the range through WITHOUT populating the cache (caching whole
        # objects on ranged access would defeat the bounded-read contract)
        with self._lock:
            data = self._cache.get(key)
            if data is not None:
                self._cache.move_to_end(key)
        if data is not None:
            OBJECT_STORE_CACHE_HITS.inc()
            return data[offset : offset + length]
        return self.inner.read_range(key, offset, length)

    def write(self, key, data):
        self.inner.write(key, data)
        self._invalidate(key)

    def put_file(self, key, local_src):
        self.inner.put_file(key, local_src)
        self._invalidate(key)

    def open_input(self, key):
        # fs returns a path — don't double-buffer that; only cache when the
        # inner store would materialize bytes anyway.
        with self._lock:
            data = self._cache.get(key)
        if data is not None:
            OBJECT_STORE_CACHE_HITS.inc()
            return pa.BufferReader(data)
        inp = self.inner.open_input(key)
        if isinstance(inp, str):
            return inp
        data = inp.read()  # drain the one buffer rather than re-reading the store
        self._put(key, data)
        return pa.BufferReader(data)

    def exists(self, key):
        with self._lock:
            if key in self._cache:
                return True
        return self.inner.exists(key)

    def delete(self, key):
        self.inner.delete(key)
        self._invalidate(key)

    def list(self, prefix=""):
        return self.inner.list(prefix)

    def size(self, key):
        with self._lock:
            data = self._cache.get(key)
        if data is not None:
            return len(data)
        return self.inner.size(key)

    def scratch_path(self, key):
        return self.inner.scratch_path(key)

    def purge_incomplete(self, prefix=""):
        self.inner.purge_incomplete(prefix)


class WriteCacheLayer(ObjectStore):
    """Local-disk staging in front of a (slow/remote) store: uploads on
    write, serves subsequent reads from disk (reference mito2
    cache/write_cache.rs:48 "upload on flush, serve reads from disk").
    Evicts least-recently-used staged files past `capacity_bytes`."""

    def __init__(self, inner: ObjectStore, cache_dir: str, capacity_bytes: int = 512 << 20):
        self.inner = inner
        self.cache_dir = cache_dir
        self.capacity = capacity_bytes
        self._lru: OrderedDict[str, int] = OrderedDict()  # key -> size
        self._used = 0
        self._lock = threading.Lock()
        os.makedirs(cache_dir, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.cache_dir, key.replace("/", "%2F"))

    def _track(self, key: str, size: int):
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._used -= old
            self._lru[key] = size
            self._used += size
            while self._used > self.capacity and len(self._lru) > 1:
                victim, vsize = self._lru.popitem(last=False)
                self._used -= vsize
                try:
                    os.remove(self._p(victim))
                except FileNotFoundError:
                    pass

    def _touch(self, key: str):
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)

    def read(self, key):
        local = self._p(key)
        if os.path.exists(local):
            OBJECT_STORE_CACHE_HITS.inc()
            self._touch(key)
            with open(local, "rb") as f:
                return f.read()
        data = self.inner.read(key)
        self._stage(local, data)
        self._track(key, len(data))
        return data

    def read_range(self, key, offset, length):
        local = self._p(key)
        if os.path.exists(local):
            OBJECT_STORE_CACHE_HITS.inc()
            self._touch(key)
            with open(local, "rb") as f:
                f.seek(offset)
                return f.read(length)
        return self.inner.read_range(key, offset, length)

    def _stage(self, local: str, data: bytes):
        # tmp+rename so concurrent readers never observe a half-written file.
        tmp = f"{local}.tmp{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, local)

    def write(self, key, data):
        self.inner.write(key, data)  # upload first: staging is a cache, not the source of truth
        self._stage(self._p(key), data)
        self._track(key, len(data))

    def put_file(self, key, local_src):
        size = os.path.getsize(local_src)
        with open(local_src, "rb") as f:
            self.inner.write(key, f.read())
        os.replace(local_src, self._p(key))
        self._track(key, size)

    def open_input(self, key):
        local = self._p(key)
        if not os.path.exists(local):
            self.read(key)  # populate staging
        else:
            OBJECT_STORE_CACHE_HITS.inc()
            self._touch(key)
        return local

    def exists(self, key):
        return os.path.exists(self._p(key)) or self.inner.exists(key)

    def delete(self, key):
        self.inner.delete(key)
        with self._lock:
            size = self._lru.pop(key, None)
            if size is not None:
                self._used -= size
        try:
            os.remove(self._p(key))
        except FileNotFoundError:
            pass

    def list(self, prefix=""):
        return self.inner.list(prefix)

    def size(self, key):
        local = self._p(key)
        if os.path.exists(local):
            return os.path.getsize(local)
        return self.inner.size(key)

    # staging files older than this are crash leftovers; in-flight _stage
    # writes live for milliseconds, so an hour protects concurrent processes
    # sharing the cache dir as well as our own threads
    PURGE_TMP_AGE_SECS = 3600

    def purge_incomplete(self, prefix=""):
        # crash leftovers: staging files that never got os.replace'd.
        # Only the exact _stage() suffix pattern, never current-process files
        # (a concurrent _stage may be mid-write), and never young files
        # (another process sharing this dir may be mid-write).
        pat = re.compile(r"\.tmp(\d+)\.\d+$")
        now = time.time()
        for name in os.listdir(self.cache_dir):
            m = pat.search(name)
            if not m or int(m.group(1)) == os.getpid():
                continue
            path = os.path.join(self.cache_dir, name)
            try:
                if now - os.path.getmtime(path) > self.PURGE_TMP_AGE_SECS:
                    os.remove(path)
            except FileNotFoundError:
                pass
        self.inner.purge_incomplete(prefix)


_REMOTE_TYPES = ("s3", "gcs", "oss", "azblob")


def build_object_store(cfg) -> ObjectStore:
    """Build the configured store + layers from a StorageConfig
    (reference object-store/src/{config,factory}.rs)."""
    kind = getattr(cfg, "store_type", "fs")
    if kind == "fs":
        store: ObjectStore = FsObjectStore(cfg.effective_sst_dir())
    elif kind in ("memory", "mock_remote"):
        if kind == "memory":
            store = MemoryObjectStore()
        else:
            # simulated remote bucket: the full remote-deployment layer
            # stack (write-cache staging + retry + LRU) runs against it
            store = SimulatedRemoteStore(
                os.path.join(cfg.data_home, "remote_bucket"),
                latency_ms=getattr(cfg, "store_mock_latency_ms", 0.0),
                fail_every=getattr(cfg, "store_mock_fail_every", 0),
            )
        if getattr(cfg, "write_cache_enable", False):
            store = WriteCacheLayer(
                store,
                os.path.join(cfg.data_home, "write_cache"),
                capacity_bytes=getattr(cfg, "write_cache_capacity_mb", 512) << 20,
            )
    elif kind == "s3" and getattr(cfg, "store_s3_endpoint", ""):
        # wire-level S3 adapter (SigV4 REST); the offline fake in
        # remote/fake_s3.py speaks the same protocol for tests.  Imported
        # lazily: remote/s3.py imports this module for the ObjectStore
        # base and counters.
        from ..remote.s3 import S3ObjectStore

        store = S3ObjectStore(
            cfg.store_s3_endpoint,
            getattr(cfg, "store_s3_bucket", "greptimedb"),
            access_key=getattr(cfg, "store_s3_access_key", ""),
            secret_key=getattr(cfg, "store_s3_secret_key", ""),
            region=getattr(cfg, "store_s3_region", "us-east-1"),
            multipart_bytes=getattr(cfg, "store_s3_multipart_mb", 8) << 20,
            pool_size=getattr(cfg, "remote_pool_size", 2),
            call_deadline_s=getattr(cfg, "remote_call_deadline_s", 5.0),
            connect_timeout_s=getattr(cfg, "remote_connect_timeout_s", 2.0),
            retry_attempts=getattr(cfg, "remote_retry_attempts", 5),
        )
        if getattr(cfg, "write_cache_enable", False):
            store = WriteCacheLayer(
                store,
                os.path.join(cfg.data_home, "write_cache"),
                capacity_bytes=getattr(cfg, "write_cache_capacity_mb", 512) << 20,
            )
    elif kind in _REMOTE_TYPES:
        raise ConfigError(
            f"object store type {kind!r} requires an endpoint and credentials "
            "(for 's3' set remote.s3_endpoint + keys — the offline fake in "
            "remote/fake_s3.py works); use 'fs', 'mock_remote' (a simulated "
            "remote exercising the same layer stack), or 'memory'. "
            "gcs/oss/azblob match the reference config surface only."
        )
    else:
        raise ConfigError(f"unknown object store type {kind!r}")
    store = RetryLayer(store, attempts=getattr(cfg, "store_retry_attempts", 3))
    cache_mb = getattr(cfg, "object_cache_mb", 0)
    if cache_mb:
        store = LruCacheLayer(store, capacity_bytes=cache_mb << 20)
    return store


class ObjectStoreManager:
    """Named stores with a default, for per-table storage selection
    (reference object-store ObjectStoreManager)."""

    def __init__(self, default: ObjectStore):
        self.default = default
        self._stores: dict[str, ObjectStore] = {}

    def register(self, name: str, store: ObjectStore):
        self._stores[name] = store

    def get(self, name: str | None) -> ObjectStore:
        if not name:
            return self.default
        try:
            return self._stores[name]
        except KeyError:
            raise ConfigError(f"unknown storage provider {name!r}") from None
