"""Persistent per-table tag dictionaries: stable codes for string group keys.

Role-equivalent of the reference's primary-key pre-encoding at write time
(reference mito-codec/src/row_converter/ — keys are encoded once, and every
consumer agrees on the encoding).  Here the unit is a per-table, per-column
dictionary: a SORTED list of distinct tag values whose position is the
value's int32 code.

Why sorted (not first-seen):
  * the storage engine sorts rows by (pk, ts); with value-sorted codes the
    group ids computed from codes are non-decreasing in scan order, which is
    exactly the layout the sorted-block aggregation kernel needs
    (ops/aggregate.py `_segment_blocked`);
  * inequality filters on tag columns (`host > 'host_5'`) become integer
    comparisons on codes — impossible with first-seen code assignment.

Growth: inserting new values shifts codes of larger values.  Each insertion
bumps `epoch` and records a permutation old-code -> new-code, so cached
device tiles encoded at an older epoch are repaired with one gather instead
of re-reading the SST (`perm_since`).  None (SQL NULL) is always the LAST
code, matching Arrow's nulls-last sort order in the memtable.
"""

from __future__ import annotations

import bisect
import json
import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


class _ColumnDict:
    def __init__(self, values: list | None = None, has_null: bool = False):
        self.values: list = values or []  # sorted, non-null values
        self.has_null = has_null
        self._value_set: pa.Array | None = None  # cache for index_in

    @property
    def size(self) -> int:
        return len(self.values) + (1 if self.has_null else 0)

    @property
    def null_code(self) -> int:
        return len(self.values) if self.has_null else -1

    def value_set(self) -> pa.Array:
        if self._value_set is None or len(self._value_set) != len(self.values):
            self._value_set = pa.array(self.values, pa.string())
        return self._value_set

    def all_values(self) -> list:
        """Code -> value list, including the None slot."""
        return self.values + ([None] if self.has_null else [])


class TableDictionary:
    """Sorted value<->code tables for every string tag column of one table."""

    def __init__(self, path: str | None = None):
        self._path = path
        self._lock = threading.RLock()
        # Coarse per-table gate for epoch-sensitive multi-step operations
        # (the tile executor holds it from tile fetch through arg packing so
        # concurrent queries can't repair shared tiles mid-pack or decode
        # against a dictionary that grew after encoding).
        self.table_lock = threading.RLock()
        self._cols: dict[str, _ColumnDict] = {}
        self.epoch = 0
        # perm history: _perms[i] maps codes at epoch i -> epoch i+1
        self._perms: dict[str, list[np.ndarray]] = {}
        if path and os.path.exists(path):
            with open(path) as f:
                d = json.load(f)
            self.epoch = int(d.get("epoch", 0))
            for name, cd in d.get("columns", {}).items():
                self._cols[name] = _ColumnDict(cd["values"], cd.get("has_null", False))

    # ---- persistence -------------------------------------------------------
    def _save_locked(self):
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "epoch": self.epoch,
                    "columns": {
                        n: {"values": c.values, "has_null": c.has_null}
                        for n, c in self._cols.items()
                    },
                },
                f,
            )
        os.replace(tmp, self._path)

    # ---- growth ------------------------------------------------------------
    def update(self, name: str, col: pa.Array | pa.ChunkedArray) -> bool:
        """Insert any unseen values of `col`; returns True if the dictionary
        grew (codes of existing values may have shifted — see perm_since)."""
        if pa.types.is_dictionary(col.type):
            col = pc.cast(col, col.type.value_type)
        uniq = pc.unique(col)
        with self._lock:
            cd = self._cols.get(name)
            if cd is None:
                cd = self._cols[name] = _ColumnDict()
            new_null = False
            if uniq.null_count and not cd.has_null:
                new_null = True
            if len(cd.values):
                hits = pc.index_in(uniq, value_set=cd.value_set())
                missing = uniq.filter(
                    pc.and_kleene(pc.is_null(hits), pc.is_valid(uniq))
                )
            else:
                missing = uniq.drop_null()
            new_vals = [v for v in missing.to_pylist()]
            if not new_vals and not new_null:
                return False
            old_values = cd.values
            old_has_null = cd.has_null
            merged = sorted(set(old_values) | set(new_vals))
            # permutation old code -> new code (None slot stays last)
            pos = {v: i for i, v in enumerate(merged)}
            perm = np.empty(len(old_values) + (1 if old_has_null else 0), np.int32)
            for i, v in enumerate(old_values):
                perm[i] = pos[v]
            if old_has_null:
                perm[len(old_values)] = len(merged)
            cd.values = merged
            cd.has_null = old_has_null or new_null
            cd._value_set = None
            self._perms.setdefault(name, [])
            # pad the history so every column's list is indexed by epoch
            while len(self._perms[name]) < self.epoch:
                self._perms[name].append(None)  # identity at that epoch
            self._perms[name].append(perm)
            for other, hist in self._perms.items():
                if other != name:
                    while len(hist) < self.epoch + 1:
                        hist.append(None)
            self.epoch += 1
            self._save_locked()
            return True

    def update_table(self, table: pa.Table, columns: list[str]) -> bool:
        grew = False
        for name in columns:
            if name in table.column_names:
                grew |= self.update(name, table[name])
        return grew

    # ---- encode ------------------------------------------------------------
    def encode(self, name: str, col: pa.Array | pa.ChunkedArray) -> np.ndarray:
        """Vectorized value->code (no Python per-row loop).  Values absent
        from the dictionary encode as -1; nulls get the null slot (or -1 if
        the column never saw a null)."""
        if pa.types.is_dictionary(col.type):
            col = pc.cast(col, col.type.value_type)
        with self._lock:
            cd = self._cols.get(name)
            if cd is None:
                return np.full(len(col), -1, np.int32)
            idx = pc.index_in(col, value_set=cd.value_set())
            out = np.asarray(
                pc.fill_null(idx, -1).to_numpy(zero_copy_only=False), np.int32
            )
            if cd.has_null:
                null_np = np.asarray(
                    pc.is_null(col).to_numpy(zero_copy_only=False), bool
                )
                out = np.where(null_np, cd.null_code, out)
            return out

    def cardinality(self, name: str) -> int:
        with self._lock:
            cd = self._cols.get(name)
            return cd.size if cd else 0

    def values(self, name: str) -> list:
        with self._lock:
            cd = self._cols.get(name)
            return cd.all_values() if cd else []

    # ---- filter literals ---------------------------------------------------
    def code_of(self, name: str, value) -> int:
        """Exact code of `value`, or -1 when absent (matches nothing)."""
        with self._lock:
            cd = self._cols.get(name)
            if cd is None:
                return -1
            if value is None:
                return cd.null_code
            i = bisect.bisect_left(cd.values, value)
            if i < len(cd.values) and cd.values[i] == value:
                return i
            return -1

    def bound(self, name: str, value) -> int:
        """Insertion point of `value` in sorted code order — lets inequality
        filters on strings run on codes: col < v  <=>  code < bound(v);
        col >= v <=> code >= bound(v); col <= v <=> code < bisect_right;
        col > v <=> code >= bisect_right."""
        with self._lock:
            cd = self._cols.get(name)
            if cd is None:
                return 0
            return bisect.bisect_left(cd.values, value)

    def bound_right(self, name: str, value) -> int:
        with self._lock:
            cd = self._cols.get(name)
            if cd is None:
                return 0
            return bisect.bisect_right(cd.values, value)

    # ---- cache repair ------------------------------------------------------
    def perm_since(self, name: str, epoch: int) -> np.ndarray | None:
        """Composed permutation mapping codes assigned at `epoch` to current
        codes; None = identity (nothing changed for this column)."""
        with self._lock:
            hist = self._perms.get(name, [])
            chain = [p for p in hist[epoch:] if p is not None]
            if not chain:
                return None
            perm = chain[0]
            for p in chain[1:]:
                # grow perm to p's domain if needed (identity on new codes)
                perm = p[perm]
            return perm


class DictionaryRegistry:
    """Per-table dictionaries living under data_home/dicts/."""

    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        self._dicts: dict[str, TableDictionary] = {}
        os.makedirs(root, exist_ok=True)

    def get(self, table_key: str) -> TableDictionary:
        with self._lock:
            d = self._dicts.get(table_key)
            if d is None:
                safe = table_key.replace("/", "%2F")
                d = self._dicts[table_key] = TableDictionary(
                    os.path.join(self.root, f"{safe}.json")
                )
            return d

    def drop(self, table_key: str):
        with self._lock:
            d = self._dicts.pop(table_key, None)
        path = os.path.join(self.root, f"{table_key.replace('/', '%2F')}.json")
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
