"""Write-ahead log: per-region append log with CRC-framed Arrow IPC entries.

Role-equivalent of the reference's local WAL (`RaftEngineLogStore`,
reference src/log-store/src/raft_engine/log_store.rs) behind the `LogStore`
trait (reference src/store-api/src/logstore.rs:51): append_batch, read from
an entry id, obsolete up to an entry id.  One log file per region; entries
are length+CRC32C framed so torn tails are detected and dropped on replay,
matching raft-engine's recovery behavior.

Frame layout (little-endian):
    [u32 payload_len][u32 crc32(payload)][u64 entry_id][payload bytes]
payload = Arrow IPC stream of one RecordBatch (the write's rows).
"""

from __future__ import annotations

import io
import os
import struct
import threading
import zlib
from dataclasses import dataclass

import pyarrow as pa

from ..utils.errors import StorageError

_HEADER = struct.Struct("<IIQ")


@dataclass
class WalEntry:
    entry_id: int
    batch: pa.RecordBatch


def _encode_batch(batch: pa.RecordBatch) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue()


def _decode_batch(payload: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        batches = list(r)
    if len(batches) != 1:
        raise StorageError(f"wal payload contained {len(batches)} batches")
    return batches[0]


class RegionWal:
    """Append log for a single region (one file, single writer)."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._file = open(path, "ab")
        # Recover last_entry_id by walking frame headers only (no Arrow
        # decode); stops at a torn tail like replay() does.
        self.last_entry_id = 0
        for entry_id in self._scan_entry_ids():
            self.last_entry_id = entry_id

    def _scan_entry_ids(self):
        # Native frame scan (greptime_native.cpp gt_wal_scan) validates
        # lengths + CRCs in C++; the Python path inside native.wal_scan is
        # the fallback when the lib is unavailable.
        if not os.path.exists(self.path):
            return
        from .. import native

        with open(self.path, "rb") as f:
            buf = f.read()
        for _off, _len, entry_id in native.wal_scan(buf):
            yield entry_id

    def advance_to(self, entry_id: int):
        """Ensure future entry ids exceed `entry_id`.  Called on region open
        with the manifest's flushed_entry_id: after obsolete() empties the
        log, a restart must not reissue ids at or below the flush watermark
        (they would be skipped by replay-from-flushed on the next recovery)."""
        with self._lock:
            self.last_entry_id = max(self.last_entry_id, entry_id)

    def append(self, batch: pa.RecordBatch) -> int:
        """Append one entry; returns its entry id."""
        payload = _encode_batch(batch)
        with self._lock:
            entry_id = self.last_entry_id + 1
            frame = _HEADER.pack(len(payload), zlib.crc32(payload), entry_id) + payload
            self._file.write(frame)
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self.last_entry_id = entry_id
            return entry_id

    def replay(self, from_entry_id: int):
        """Yield entries with id > from_entry_id; stop at a torn/corrupt tail."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            while True:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, crc, entry_id = _HEADER.unpack(header)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break  # torn write at tail — recovery stops here
                if entry_id > from_entry_id:
                    yield WalEntry(entry_id, _decode_batch(payload))

    def obsolete(self, up_to_entry_id: int):
        """Drop entries <= up_to_entry_id (called after flush, reference
        store-api/src/logstore.rs:79-82).  Rewrites the log without them."""
        with self._lock:
            keep = [e for e in self.replay(up_to_entry_id)]
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                for e in keep:
                    payload = _encode_batch(e.batch)
                    f.write(_HEADER.pack(len(payload), zlib.crc32(payload), e.entry_id) + payload)
                f.flush()
                os.fsync(f.fileno())
            self._file.close()
            os.replace(tmp, self.path)
            self._file = open(self.path, "ab")

    def close(self):
        with self._lock:
            self._file.close()


class WalManager:
    """LogStore facade handing out per-region logs under one directory."""

    def __init__(self, wal_dir: str, fsync: bool = False):
        self.wal_dir = wal_dir
        self.fsync = fsync
        self._regions: dict[int, RegionWal] = {}
        self._lock = threading.Lock()

    def region_wal(self, region_id: int) -> RegionWal:
        with self._lock:
            wal = self._regions.get(region_id)
            if wal is None:
                path = os.path.join(self.wal_dir, f"region_{region_id}.wal")
                wal = RegionWal(path, fsync=self.fsync)
                self._regions[region_id] = wal
            return wal

    def drop_region(self, region_id: int):
        with self._lock:
            wal = self._regions.pop(region_id, None)
        if wal is not None:
            wal.close()
            if os.path.exists(wal.path):
                os.remove(wal.path)

    def close(self):
        with self._lock:
            for wal in self._regions.values():
                wal.close()
            self._regions.clear()
