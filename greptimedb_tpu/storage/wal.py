"""Write-ahead log: per-region append log with CRC-framed Arrow IPC entries.

Role-equivalent of the reference's local WAL (`RaftEngineLogStore`,
reference src/log-store/src/raft_engine/log_store.rs) behind the `LogStore`
trait (reference src/store-api/src/logstore.rs:51): append_batch, read from
an entry id, obsolete up to an entry id.  One log file per region; entries
are length+CRC32C framed so torn tails are detected and dropped on replay,
matching raft-engine's recovery behavior.

Frame layout (little-endian):
    [u32 payload_len][u32 crc32(payload)][u64 entry_id][payload bytes]
payload = Arrow IPC stream of one RecordBatch (the write's rows).
"""

from __future__ import annotations

import io
import os
import struct
import threading
import zlib
from dataclasses import dataclass

import pyarrow as pa

from ..utils import metrics
from ..utils.errors import StorageError

_HEADER = struct.Struct("<IIQ")

# Group-commit frames (ingest.group_commit): ONE frame carries a whole
# region-worker drain group — one Arrow IPC encode, one write syscall, one
# optional fsync — while every write in the group keeps its own entry id.
# The header's entry_id field carries the LAST id of the group with this
# bit set (bit 62, not 63: the native wal_scan returns ids through signed
# int64 slots); the payload leads with [u32 n][u32 rows_i]* then one IPC
# stream of the concatenated rows.  Replay slices the decoded batch back
# into per-write entries, so everything downstream of replay — recovery,
# follower lag accounting, shared-WAL pruning — sees the same entries as
# frame-per-write.  A torn tail drops the WHOLE group (all-or-nothing),
# exactly like a torn solo frame drops its write.
GROUP_FLAG = 1 << 62
_GROUP_HEAD = struct.Struct("<I")


@dataclass
class WalEntry:
    entry_id: int
    batch: pa.RecordBatch


def _encode_batch(batch: pa.RecordBatch) -> pa.Buffer:
    """One IPC stream encode into an arrow Buffer — no BytesIO copy, and
    callers write header and payload as separate syscalls instead of
    concatenating (a 200 MB batch used to pay THREE extra full copies
    per append).  The Buffer supports len()/crc32/file.write directly."""
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    return sink.getvalue()


def _decode_batch(payload: bytes) -> pa.RecordBatch:
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        batches = list(r)
    if len(batches) != 1:
        raise StorageError(f"wal payload contained {len(batches)} batches")
    return batches[0]


def _encode_group(batches: list[pa.RecordBatch]) -> tuple[bytes, pa.Buffer]:
    """Group payload: [u32 n][u32 rows_i]* + ONE IPC stream of the
    concatenated rows (the single encode group commit exists for).
    Returned as (head, ipc_buffer) so writers emit both without a
    payload-sized concat copy."""
    if len(batches) == 1:
        merged = batches[0]
    else:
        t = pa.Table.from_batches(batches).combine_chunks()
        merged = t.to_batches()[0] if t.num_rows else batches[0].slice(0, 0)
    head = [_GROUP_HEAD.pack(len(batches))]
    head += [_GROUP_HEAD.pack(b.num_rows) for b in batches]
    return b"".join(head), _encode_batch(merged)


def _decode_group(payload: bytes) -> list[pa.RecordBatch]:
    """Inverse of `_encode_group`: one decode, zero-copy per-write slices."""
    (n,) = _GROUP_HEAD.unpack_from(payload, 0)
    off = _GROUP_HEAD.size
    rows = []
    for _ in range(n):
        (r,) = _GROUP_HEAD.unpack_from(payload, off)
        rows.append(r)
        off += _GROUP_HEAD.size
    merged = _decode_batch(payload[off:])
    out, pos = [], 0
    for r in rows:
        out.append(merged.slice(pos, r))
        pos += r
    return out


class RegionWal:
    """Append log for a single region (one file, single writer)."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._file = open(path, "ab")
        # Recover last_entry_id by walking frame headers only (no Arrow
        # decode); stops at a torn tail like replay() does.
        self.last_entry_id = 0
        for entry_id in self._scan_entry_ids():
            self.last_entry_id = entry_id

    def _scan_entry_ids(self):
        # Native frame scan (greptime_native.cpp gt_wal_scan) validates
        # lengths + CRCs in C++; the Python path inside native.wal_scan is
        # the fallback when the lib is unavailable.
        if not os.path.exists(self.path):
            return
        from .. import native

        with open(self.path, "rb") as f:
            buf = f.read()
        for _off, _len, entry_id in native.wal_scan(buf):
            # a group frame's header carries the LAST id of its group, so
            # masking the flag keeps last-entry-id recovery exact
            yield entry_id & ~GROUP_FLAG

    def advance_to(self, entry_id: int):
        """Ensure future entry ids exceed `entry_id`.  Called on region open
        with the manifest's flushed_entry_id: after obsolete() empties the
        log, a restart must not reissue ids at or below the flush watermark
        (they would be skipped by replay-from-flushed on the next recovery)."""
        with self._lock:
            self.last_entry_id = max(self.last_entry_id, entry_id)

    def append(self, batch: pa.RecordBatch) -> int:
        """Append one entry; returns its entry id."""
        payload = _encode_batch(batch)
        crc = zlib.crc32(memoryview(payload))
        with self._lock:
            entry_id = self.last_entry_id + 1
            self._file.write(_HEADER.pack(len(payload), crc, entry_id))
            self._file.write(payload)
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self.last_entry_id = entry_id
        metrics.INGEST_WAL_FRAMES.inc()
        metrics.INGEST_WAL_BYTES.inc(_HEADER.size + len(payload))
        return entry_id

    def append_group(self, batches: list[pa.RecordBatch]) -> list[int]:
        """Append a drain group as ONE frame; every batch keeps its own
        entry id (returned in order).  One IPC encode, one write, one
        optional fsync — the acks this call unblocks are still durable
        per write, because they all happen after the group's fsync."""
        if len(batches) == 1:
            return [self.append(batches[0])]
        head, ipc = _encode_group(batches)
        length = len(head) + len(ipc)
        crc = zlib.crc32(memoryview(ipc), zlib.crc32(head))
        with self._lock:
            first = self.last_entry_id + 1
            last = self.last_entry_id + len(batches)
            self._file.write(_HEADER.pack(length, crc, last | GROUP_FLAG))
            self._file.write(head)
            self._file.write(ipc)
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self.last_entry_id = last
        metrics.INGEST_WAL_FRAMES.inc()
        metrics.INGEST_WAL_BYTES.inc(_HEADER.size + length)
        metrics.INGEST_GROUP_FRAMES.inc()
        metrics.INGEST_GROUP_WRITES.inc(len(batches))
        return list(range(first, last + 1))

    def replay(self, from_entry_id: int):
        """Yield entries with id > from_entry_id; stop at a torn/corrupt tail."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            while True:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, crc, entry_id = _HEADER.unpack(header)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break  # torn write at tail — recovery stops here
                if entry_id & GROUP_FLAG:
                    last = entry_id & ~GROUP_FLAG
                    subs = _decode_group(payload)
                    first = last - len(subs) + 1
                    for i, b in enumerate(subs):
                        if first + i > from_entry_id:
                            yield WalEntry(first + i, b)
                elif entry_id > from_entry_id:
                    yield WalEntry(entry_id, _decode_batch(payload))

    def obsolete(self, up_to_entry_id: int):
        """Drop entries <= up_to_entry_id (called after flush, reference
        store-api/src/logstore.rs:79-82).  Rewrites the log without them."""
        with self._lock:
            keep = [e for e in self.replay(up_to_entry_id)]
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                for e in keep:
                    payload = _encode_batch(e.batch)
                    f.write(_HEADER.pack(
                        len(payload), zlib.crc32(memoryview(payload)), e.entry_id
                    ))
                    f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            self._file.close()
            os.replace(tmp, self.path)
            self._file = open(self.path, "ab")

    def close(self):
        with self._lock:
            self._file.close()


class WalManager:
    """LogStore facade handing out per-region logs under one directory."""

    def __init__(self, wal_dir: str, fsync: bool = False):
        self.wal_dir = wal_dir
        self.fsync = fsync
        self._regions: dict[int, RegionWal] = {}
        self._lock = threading.Lock()

    def region_wal(self, region_id: int) -> RegionWal:
        with self._lock:
            wal = self._regions.get(region_id)
            if wal is None:
                path = os.path.join(self.wal_dir, f"region_{region_id}.wal")
                wal = RegionWal(path, fsync=self.fsync)
                self._regions[region_id] = wal
            return wal

    def drop_region(self, region_id: int):
        with self._lock:
            wal = self._regions.pop(region_id, None)
        if wal is not None:
            wal.close()
            if os.path.exists(wal.path):
                os.remove(wal.path)

    def close(self):
        with self._lock:
            for wal in self._regions.values():
                wal.close()
            self._regions.clear()
