"""Region manifest: an append log of metadata actions with checkpoints.

Role-equivalent of the reference's `RegionManifestManager` (reference
src/mito2/src/manifest/manager.rs:152): every region mutation (flush adds
files, compaction swaps files, truncate clears) appends a `RegionMetaAction`
delta; every `checkpoint_distance` versions the full state is compacted into
a checkpoint so region open replays O(checkpoint_distance) deltas instead of
the whole history.
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from dataclasses import dataclass, field

from ..datatypes.schema import Schema
from ..utils.errors import StorageError
from .sst import FileMeta


@dataclass
class RegionManifest:
    """Materialized manifest state (reference manifest/action.rs:118)."""

    region_id: int
    schema: Schema | None = None
    files: dict[str, FileMeta] = field(default_factory=dict)
    flushed_entry_id: int = 0
    flushed_sequence: int = 0
    manifest_version: int = 0
    truncated_entry_id: int | None = None

    def to_dict(self) -> dict:
        return {
            "region_id": self.region_id,
            "schema": self.schema.to_json() if self.schema else None,
            "files": {k: v.to_dict() for k, v in self.files.items()},
            "flushed_entry_id": self.flushed_entry_id,
            "flushed_sequence": self.flushed_sequence,
            "manifest_version": self.manifest_version,
            "truncated_entry_id": self.truncated_entry_id,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RegionManifest":
        return cls(
            region_id=d["region_id"],
            schema=Schema.from_json(d["schema"]) if d.get("schema") else None,
            files={k: FileMeta.from_dict(v) for k, v in d["files"].items()},
            flushed_entry_id=d.get("flushed_entry_id", 0),
            flushed_sequence=d.get("flushed_sequence", 0),
            manifest_version=d.get("manifest_version", 0),
            truncated_entry_id=d.get("truncated_entry_id"),
        )


class ManifestManager:
    """Per-region manifest log under `{dir}/manifest/`.

    Files: `{version:020d}.json` delta actions, `{version:020d}.checkpoint.json`
    checkpoints (full state).  Recovery loads the newest checkpoint then
    replays newer deltas, exactly the reference's scheme.
    """

    def __init__(
        self,
        store_or_dir,
        region_id: int,
        checkpoint_distance: int = 10,
    ):
        from .object_store import FsObjectStore, ObjectStore

        if isinstance(store_or_dir, ObjectStore):
            self.store = store_or_dir.scoped("manifest")
        else:
            self.store = FsObjectStore(os.path.join(store_or_dir, "manifest"))
        self.region_id = region_id
        self.checkpoint_distance = checkpoint_distance
        self._lock = threading.Lock()
        # A crash mid-write can leave fs .tmp leftovers; clean them before
        # recovery so they never accumulate (the pre-object-store code did
        # this during checkpoint GC).
        self.store.purge_incomplete()
        self.manifest = self._recover()

    # ---- actions ----------------------------------------------------------
    def apply(self, action: dict) -> RegionManifest:
        """Append one action and apply it to the in-memory state.

        Action kinds (reference RegionMetaAction):
          {"kind": "change", "schema": <json>}                      — DDL
          {"kind": "edit", "files_to_add": [...], "files_to_remove": [...],
           "flushed_entry_id": N, "flushed_sequence": N}            — flush/compaction
          {"kind": "truncate", "truncated_entry_id": N}             — truncate
        """
        with self._lock:
            version = self.manifest.manifest_version + 1
            # writer-unique suffix: two region holders racing one version
            # slot (transient split-brain during failover) must never
            # OVERWRITE each other's edit — a lost files_to_remove leaves
            # the manifest referencing deleted SSTs forever.  Both edits
            # survive and replay deterministically; adds/removes are
            # idempotent under re-application.
            uid = uuid.uuid4().hex[:8]
            self.store.write(
                f"{version:020d}.{uid}.json", json.dumps(action).encode()
            )
            self._apply_in_memory(action, version)
            if version % self.checkpoint_distance == 0:
                self._write_checkpoint()
            return self.manifest

    def _apply_in_memory(self, action: dict, version: int, manifest: RegionManifest | None = None):
        m = manifest if manifest is not None else self.manifest
        kind = action.get("kind")
        if kind == "change":
            m.schema = Schema.from_json(action["schema"])
        elif kind == "edit":
            anchor = action.get("insert_at")
            if anchor is not None and anchor in m.files:
                # ordered insertion (compaction): the merged output takes
                # the manifest position of its NEWEST input, so files
                # flushed DURING the merge stay newer than it — scans rank
                # duplicate (pk, ts) versions by manifest position, and an
                # appended output would beat data that overwrote its
                # inputs mid-compaction.  Dict rebuild preserves replay
                # determinism (the anchor rides the persisted action).
                rebuilt: dict[str, FileMeta] = {}
                removes = set(action.get("files_to_remove", []))
                for k, v in m.files.items():
                    if k == anchor:
                        # adds insert AT the anchor's slot (before it, if
                        # the anchor itself survives the edit)
                        for fd in action.get("files_to_add", []):
                            meta = FileMeta.from_dict(fd)
                            rebuilt[meta.file_id] = meta
                    if k not in removes:
                        rebuilt[k] = v
                m.files = rebuilt
            else:
                for fd in action.get("files_to_add", []):
                    meta = FileMeta.from_dict(fd)
                    m.files[meta.file_id] = meta
            for fid in action.get("files_to_remove", []):
                m.files.pop(fid, None)
            if action.get("flushed_entry_id") is not None:
                m.flushed_entry_id = max(m.flushed_entry_id, action["flushed_entry_id"])
            if action.get("flushed_sequence") is not None:
                m.flushed_sequence = max(m.flushed_sequence, action["flushed_sequence"])
        elif kind == "truncate":
            m.files.clear()
            m.truncated_entry_id = action.get("truncated_entry_id")
            m.flushed_entry_id = max(m.flushed_entry_id, action.get("truncated_entry_id") or 0)
        else:
            raise StorageError(f"unknown manifest action kind: {kind}")
        m.manifest_version = version

    # ---- checkpointing / recovery -----------------------------------------
    def _write_checkpoint(self):
        version = self.manifest.manifest_version
        # uid keeps two holders' same-version checkpoints from silently
        # overwriting each other; recovery picks the lexically-last
        self.store.write(
            f"{version:020d}.{uuid.uuid4().hex[:8]}.checkpoint.json",
            json.dumps(self.manifest.to_dict()).encode(),
        )
        # GC keeps a TRAILING WINDOW of deltas (2x checkpoint distance)
        # below the checkpoint, not just same-version ones: a concurrent
        # holder (transient split-brain) may have written edits at any
        # recent version our checkpoint never saw — deleting them loses
        # file adds/removes permanently.  The alive keeper closes stale
        # holders within seconds, so the window comfortably covers the
        # race; replay re-applies windowed deltas idempotently.
        keep_from = version - 2 * self.checkpoint_distance
        for name in self.store.list():
            v = _version_of(name)
            if v is not None and v < keep_from:
                self.store.delete(name)

    def _recover(self) -> RegionManifest:
        names = [n for n in self.store.list() if n.endswith(".json")]
        ckpts = sorted(n for n in names if n.endswith(".checkpoint.json"))
        deltas = sorted(n for n in names if not n.endswith(".checkpoint.json"))
        manifest = RegionManifest(region_id=self.region_id)
        base_version = 0
        if ckpts:
            manifest = RegionManifest.from_dict(json.loads(self.store.read(ckpts[-1])))
            base_version = manifest.manifest_version
        for name in deltas:
            v = _version_of(name)
            # re-apply the trailing delta window over the checkpoint
            # (idempotent adds/removes; concurrent-holder edits the
            # checkpoint never saw get incorporated here)
            if v is None or v < base_version - 2 * self.checkpoint_distance:
                continue
            action = json.loads(self.store.read(name))
            self._apply_in_memory(action, v, manifest=manifest)
        return manifest

    def refresh(self) -> tuple[RegionManifest, bool]:
        """Re-read the manifest log from shared storage and adopt the fresh
        state when another holder (the region's LEADER — this is the
        follower-replica path) advanced it; returns (manifest, changed).

        Read-only by construction: recovery never writes, so a follower can
        refresh on a cadence without racing the leader's appends.  A delta
        or checkpoint GC'd between list and read surfaces as a transient
        error the caller retries next round — the previous view stays
        installed, never a half-applied one."""
        with self._lock:
            fresh = self._recover()
            if fresh.manifest_version <= self.manifest.manifest_version:
                return self.manifest, False
            self.manifest = fresh
            return fresh, True

    def destroy(self):
        for name in self.store.list():
            self.store.delete(name)


def _version_of(name: str) -> int | None:
    stem = name.split(".")[0]
    return int(stem) if stem.isdigit() else None
