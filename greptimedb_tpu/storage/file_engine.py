"""File engine: external tables over CSV/NDJSON/Parquet files.

Role-equivalent of the reference's file engine + datasource layer
(reference src/file-engine/src/engine.rs `FileRegionEngine`,
common/datasource): `CREATE EXTERNAL TABLE` registers a read-only table
whose scans decode files on demand — no regions, no WAL.  Also provides
the format codecs used by `COPY table TO/FROM` (reference
operator/src/statement/copy_table_{from,to}.rs).
"""

from __future__ import annotations

import glob as _glob
import json as _json
import os

import pyarrow as pa
import pyarrow.csv as pa_csv
import pyarrow.parquet as pq

from ..datatypes.data_type import ConcreteDataType
from ..datatypes.schema import ColumnSchema, Schema, SemanticType
from ..utils.errors import InvalidArgumentsError

LOCATION_OPT = "__external_location"
FORMAT_OPT = "__external_format"

FORMATS = ("parquet", "csv", "json")

_EXTENSIONS = {".parquet": "parquet", ".csv": "csv", ".json": "json", ".ndjson": "json"}


def detect_format(path: str, explicit: str | None = None) -> str:
    if explicit:
        f = explicit.lower()
        if f not in FORMATS:
            raise InvalidArgumentsError(
                f"unsupported format {explicit!r} (use parquet/csv/json)"
            )
        return f
    ext = os.path.splitext(path)[1].lower()
    if ext in _EXTENSIONS:
        return _EXTENSIONS[ext]
    raise InvalidArgumentsError(
        f"cannot infer format from {path!r}; pass WITH (format = '...')"
    )


def expand_location(location: str) -> list[str]:
    """A file, a directory (all supported files inside), or a glob."""
    if os.path.isdir(location):
        out = [
            os.path.join(location, f)
            for f in sorted(os.listdir(location))
            if os.path.splitext(f)[1].lower() in _EXTENSIONS
        ]
        if not out:
            raise InvalidArgumentsError(f"no data files in directory {location!r}")
        return out
    if any(c in location for c in "*?["):
        out = sorted(_glob.glob(location))
        if not out:
            raise InvalidArgumentsError(f"glob matched no files: {location!r}")
        return out
    if not os.path.exists(location):
        raise InvalidArgumentsError(f"no such file: {location!r}")
    return [location]


def read_file(path: str, fmt: str) -> pa.Table:
    if fmt == "parquet":
        return pq.read_table(path)
    if fmt == "csv":
        return pa_csv.read_csv(path)
    if fmt == "json":
        import pyarrow.json as pa_json

        return pa_json.read_json(path)
    raise InvalidArgumentsError(f"unsupported format {fmt!r}")


def write_file(table: pa.Table, path: str, fmt: str):
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    if fmt == "parquet":
        pq.write_table(table, path, compression="zstd")
    elif fmt == "csv":
        pa_csv.write_csv(table, path)
    elif fmt == "json":
        with open(path, "w") as f:
            for row in table.to_pylist():
                f.write(_json.dumps(row, default=str) + "\n")
    else:
        raise InvalidArgumentsError(f"unsupported format {fmt!r}")


def infer_schema(location: str, fmt: str) -> Schema:
    """Derive a Schema from the first file: the first timestamp-typed column
    becomes the time index, everything else a FIELD (reference file-engine
    infers the arrow schema from the file the same way)."""
    first = expand_location(location)[0]
    if fmt == "parquet":
        arrow_schema = pq.read_schema(first)  # footer only, no data decode
    else:
        arrow_schema = read_file(first, fmt).schema
    cols = []
    ts_seen = False
    for f in arrow_schema:
        dt = ConcreteDataType.from_arrow(f.type)
        if not ts_seen and pa.types.is_timestamp(f.type):
            cols.append(ColumnSchema(f.name, dt, SemanticType.TIMESTAMP))
            ts_seen = True
        else:
            cols.append(ColumnSchema(f.name, dt, SemanticType.FIELD))
    return Schema(columns=cols)


def time_bounds(meta) -> tuple[int, int] | None:
    """Min/max of the time index.  Parquet answers from row-group footer
    statistics without decoding data; other formats fall back to a scan."""
    ts = meta.schema.time_index
    if ts is None:
        return None
    fmt = meta.options[FORMAT_OPT]
    unit_ns = ts.data_type.timestamp_unit_ns()
    lo = hi = None
    if fmt == "parquet":
        from .sst import _ts_to_int

        for path in expand_location(meta.options[LOCATION_OPT]):
            pf = pq.ParquetFile(path)
            idx = pf.schema_arrow.get_field_index(ts.name)
            if idx < 0:
                continue
            for g in range(pf.metadata.num_row_groups):
                stats = pf.metadata.row_group(g).column(idx).statistics
                if stats is None or not stats.has_min_max:
                    return _scan_bounds(meta, ts, unit_ns)  # stats missing
                g_min = _ts_to_int(stats.min, unit_ns)
                g_max = _ts_to_int(stats.max, unit_ns)
                lo = g_min if lo is None else min(lo, g_min)
                hi = g_max if hi is None else max(hi, g_max)
        return None if lo is None else (lo, hi)
    return _scan_bounds(meta, ts, unit_ns)


def _scan_bounds(meta, ts, unit_ns) -> tuple[int, int] | None:
    import pyarrow.compute as pc

    t = scan(meta)
    if t.num_rows == 0:
        return None
    col = pc.cast(t[ts.name], pa.int64())
    return (pc.min(col).as_py(), pc.max(col).as_py())


def is_external_meta(meta) -> bool:
    return LOCATION_OPT in meta.options


def scan(meta, pred=None) -> pa.Table:
    """Scan an external table: read every file, conform to the declared
    schema, apply pushed-down predicates."""
    from .sst import ScanPredicate, _apply_residual

    location = meta.options[LOCATION_OPT]
    fmt = meta.options[FORMAT_OPT]
    tables = []
    want = meta.schema.to_arrow()
    for path in expand_location(location):
        t = read_file(path, fmt)
        # project/cast to the declared columns (extra file columns dropped)
        arrays, fields = [], []
        for f in want:
            i = t.schema.get_field_index(f.name)
            if i >= 0:
                col = t.column(i)
                arrays.append(col if col.type == f.type else col.cast(f.type))
            else:
                arrays.append(pa.nulls(t.num_rows, f.type))
            fields.append(f)
        tables.append(pa.table(dict(zip([f.name for f in fields], arrays))))
    out = pa.concat_tables(tables, promote_options="permissive")
    ts = meta.schema.time_index
    return _apply_residual(out, pred or ScanPredicate(), ts.name if ts else None)
