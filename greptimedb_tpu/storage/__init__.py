from .engine import TimeSeriesEngine

__all__ = ["TimeSeriesEngine"]
