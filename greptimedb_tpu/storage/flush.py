"""Write buffer accounting + flush policy.

Role-equivalent of the reference's `WriteBufferManagerImpl`
(reference src/mito2/src/flush.rs:107): tracks global mutable memtable
memory, decides when the engine should flush (`should_flush_engine`,
flush.rs:152) and when writes must stall (`should_stall`, flush.rs:173).
"""

from __future__ import annotations

import threading


class WriteBufferManager:
    def __init__(self, global_limit_bytes: int, region_limit_bytes: int):
        self.global_limit = global_limit_bytes
        self.region_limit = region_limit_bytes
        self._mutable: dict[int, int] = {}  # region_id -> bytes
        self._lock = threading.Lock()

    def set_region_usage(self, region_id: int, bytes_: int):
        with self._lock:
            self._mutable[region_id] = bytes_

    def remove_region(self, region_id: int):
        with self._lock:
            self._mutable.pop(region_id, None)

    def mutable_usage(self) -> int:
        with self._lock:
            return sum(self._mutable.values())

    def region_usage(self, region_id: int) -> int:
        with self._lock:
            return self._mutable.get(region_id, 0)

    def should_flush_region(self, region_id: int) -> bool:
        return self.region_usage(region_id) >= self.region_limit

    def should_flush_engine(self) -> bool:
        # Reference flushes when global mutable usage crosses 7/8 of limit.
        return self.mutable_usage() >= self.global_limit * 7 // 8

    def should_stall(self) -> bool:
        return self.mutable_usage() >= self.global_limit

    def pick_flush_candidates(self) -> list[int]:
        """Regions to flush, largest first (greedy pressure relief)."""
        with self._lock:
            return [
                rid
                for rid, b in sorted(self._mutable.items(), key=lambda kv: -kv[1])
                if b > 0
            ]
