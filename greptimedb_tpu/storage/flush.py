"""Write buffer accounting + flush policy.

Role-equivalent of the reference's `WriteBufferManagerImpl`
(reference src/mito2/src/flush.rs:107): tracks global mutable memtable
memory, decides when the engine should flush (`should_flush_engine`,
flush.rs:152) and when writes must stall (`should_stall`, flush.rs:173).
"""

from __future__ import annotations

import threading


class WriteBufferManager:
    def __init__(self, global_limit_bytes: int, region_limit_bytes: int):
        self.global_limit = global_limit_bytes
        self.region_limit = region_limit_bytes
        self._mutable: dict[int, int] = {}  # region_id -> bytes
        # Bytes frozen for an in-flight flush encode (ingest.flush_overlap):
        # they left the mutable budget at freeze time so new writes keep
        # being admitted during the encode, but still count against the
        # hard 2x bound so a slow flush cannot let memory grow unbounded.
        self._flushing: dict[int, int] = {}
        self._lock = threading.Lock()

    def set_region_usage(self, region_id: int, bytes_: int):
        with self._lock:
            self._mutable[region_id] = bytes_

    def remove_region(self, region_id: int):
        with self._lock:
            self._mutable.pop(region_id, None)
            self._flushing.pop(region_id, None)

    def freeze_region(self, region_id: int, bytes_: int):
        """A flush froze `bytes_` of this region's memtable: move them
        from the mutable budget to the flushing bucket (called under the
        region lock, at the same instant the fresh memtable is swapped in)."""
        with self._lock:
            self._flushing[region_id] = self._flushing.get(region_id, 0) + bytes_
            cur = self._mutable.get(region_id, 0)
            self._mutable[region_id] = max(0, cur - bytes_)

    def unfreeze_region(self, region_id: int, bytes_: int):
        """The flush encode finished (committed or discarded): release the
        frozen bytes."""
        with self._lock:
            left = self._flushing.get(region_id, 0) - bytes_
            if left > 0:
                self._flushing[region_id] = left
            else:
                self._flushing.pop(region_id, None)

    def mutable_usage(self) -> int:
        with self._lock:
            return sum(self._mutable.values())

    def flushing_usage(self) -> int:
        with self._lock:
            return sum(self._flushing.values())

    def region_usage(self, region_id: int) -> int:
        with self._lock:
            return self._mutable.get(region_id, 0)

    def should_flush_region(self, region_id: int) -> bool:
        return self.region_usage(region_id) >= self.region_limit

    def should_flush_engine(self) -> bool:
        # Reference flushes when global mutable usage crosses 7/8 of limit.
        return self.mutable_usage() >= self.global_limit * 7 // 8

    def should_stall(self) -> bool:
        with self._lock:
            mutable = sum(self._mutable.values())
            flushing = sum(self._flushing.values())
        # Mutable alone over the limit stalls (the pre-overlap rule); with
        # flush overlap the frozen bytes no longer count as mutable, so
        # ingest keeps running during an encode — until total memory
        # (mutable + in-flight flush) hits the 2x hard bound.
        return (
            mutable >= self.global_limit
            or mutable + flushing >= self.global_limit * 2
        )

    def pick_flush_candidates(self) -> list[int]:
        """Regions to flush, largest first (greedy pressure relief)."""
        with self._lock:
            return [
                rid
                for rid, b in sorted(self._mutable.items(), key=lambda kv: -kv[1])
                if b > 0
            ]
