"""Region: the unit of storage, replication and scan parallelism.

Role-equivalent of the reference's `MitoRegion` (reference
src/mito2/src/region.rs:121) plus its opener (region/opener.rs): a region
owns a WAL stream, an active memtable, a set of immutable SSTs tracked by a
manifest, and a monotonically increasing sequence number.  Writes go
WAL-then-memtable (reference worker/handle_write.rs:83-135); flush turns the
memtable into time-window-aligned SSTs and advances `flushed_entry_id` so
the WAL can be truncated; open replays manifest then WAL from
`flushed_entry_id` (reference region/opener.rs:500-516).

Concurrency model: like the reference's single-writer-per-region actor
(worker.rs:459), all mutations take the region write lock; scans only read
immutable snapshots (memtable materialization + SST list copy).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

import numpy as np
import pyarrow as pa

from ..datatypes.schema import Schema
from ..utils import metrics
from ..utils.deadline import check_deadline
from ..utils.errors import IllegalStateError, RegionReadonlyError
from .manifest import ManifestManager
from .memtable import Memtable, make_memtable
from .sst import FileMeta, ScanPredicate, SstReader, SstWriter
from .wal import RegionWal

# Per-row operation marker carried through memtable, WAL and SSTs
# (reference api::v1::OpType / mito2 key-value op types): 0 = put,
# 1 = delete tombstone.  Tombstones win dedup (they carry a later
# sequence) and are dropped from scan output; they persist through
# flush/compaction so deletes survive restarts and file merges.
OP_COL = "__op"
OP_PUT = 0
OP_DELETE = 1


@dataclass
class RegionStat:
    region_id: int
    num_rows: int
    sst_count: int
    sst_bytes: int
    memtable_bytes: int
    wal_entry_id: int
    flushed_entry_id: int
    # follower-replica fields (ride heartbeat stats to the metasrv so the
    # frontend can gate hedging on staleness): lag_ms is milliseconds since
    # the last successful WAL-tail sync; lag_entries is best-effort (the
    # log head is only observed at sync time)
    writable: bool = True
    follower_lag_entries: int = 0
    follower_lag_ms: float = 0.0


class Region:
    def __init__(
        self,
        region_id: int,
        region_dir: str,
        schema: Schema,
        wal: RegionWal,
        *,
        time_partition_ms: int = 86_400_000,
        checkpoint_distance: int = 10,
        writable: bool = True,
        index_enable: bool = True,
        index_segment_rows: int = 1024,
        index_inverted_max_terms: int = 4096,
        index_segmented: bool = True,
        index_segment_terms: int = 512,
        index_max_terms: int = 1 << 20,
        append_mode: bool = False,
        merge_mode: str | None = None,
        memtable_kind: str = "time_partition",
        flush_workers: int = 1,
    ):
        from .object_store import FsObjectStore, ObjectStore

        self.region_id = region_id
        # `region_dir` may be a local path (standalone default) or an
        # ObjectStore view for this region (reference: SSTs+manifest live on
        # object storage; only the WAL is local).
        if isinstance(region_dir, ObjectStore):
            self.store: ObjectStore = region_dir
            self.region_dir = None
        else:
            self.store = FsObjectStore(region_dir)
            self.region_dir = region_dir
        self.wal = wal
        self.time_partition_ms = time_partition_ms
        self._lock = threading.RLock()
        self.writable = writable  # follower replicas are read-only
        # Serializes compaction drivers (background scheduler vs ADMIN
        # compact_table): two concurrent rounds would pick the same L0
        # group and commit the merged rows twice.
        self.compaction_lock = threading.Lock()
        # Dedup strategy (reference mito2 `merge_mode` table option):
        # "last_row" keeps the newest version whole; "last_non_null"
        # merges fieldwise — the newest NON-NULL value per field wins
        # (read/dedup.rs LastNonNull).
        self.merge_mode = merge_mode or "last_row"
        # Append-only mode (reference mito2 `append_mode` table option):
        # duplicates are kept (no last-write-wins dedup) and DELETE is
        # rejected — the shape log/trace workloads want, and the condition
        # under which the device tile cache can aggregate SSTs directly.
        self.append_mode = append_mode

        self.manifest_mgr = ManifestManager(self.store, region_id, checkpoint_distance)
        if self.manifest_mgr.manifest.schema is None:
            self.manifest_mgr.apply({"kind": "change", "schema": schema.to_json()})
        self.schema = self.manifest_mgr.manifest.schema
        sst_store = self.store.scoped("sst")
        self.sst_writer = SstWriter(
            sst_store,
            self.schema,
            index_enable=index_enable,
            index_segment_rows=index_segment_rows,
            index_inverted_max_terms=index_inverted_max_terms,
            index_segmented=index_segmented,
            index_segment_terms=index_segment_terms,
            index_max_terms=index_max_terms,
        )
        self.sst_reader = SstReader(sst_store, self.schema)

        self.memtable_kind = memtable_kind
        self.memtable = make_memtable(self.schema, time_partition_ms, memtable_kind)
        # Frozen memtables: flushed but whose SSTs are not yet committed to the
        # manifest; readable by scans so flush never opens a visibility gap.
        self._frozen_memtables: list[Memtable] = []
        # SSTs removed from the manifest but not yet safe to delete (readers
        # in flight may hold the old file list); purged when readers drain.
        # (file_id, tombstoned_at): physical deletion waits out BOTH
        # local in-flight scans AND a wall-clock grace, because ANOTHER
        # region holder (transient split-brain during failover, or a
        # second process on shared storage) may still scan from an older
        # manifest snapshot that references these files (the reference's
        # file purger + object-store GC grace plays the same role)
        self._garbage_files: list[tuple[str, float]] = []
        self.gc_grace_secs: float = 60.0
        self._active_scans = 0
        self.sequence = self.manifest_mgr.manifest.flushed_sequence
        # Future WAL entry ids must exceed the flush watermark, else writes
        # after an obsolete()+restart would replay below it and be lost.
        self.wal.advance_to(
            max(
                self.manifest_mgr.manifest.flushed_entry_id,
                self.manifest_mgr.manifest.truncated_entry_id or 0,
            )
        )
        # Replay progress marker: the highest WAL entry id applied to this
        # region's memtable.  Leaders advance it on every write; followers
        # advance it as follower_sync() tails the shared log, and the
        # shared-WAL prune keeps everything a registered follower has not
        # yet applied.
        self.applied_entry_id = 0
        self.last_sync_ms = time.time() * 1000
        # set once the follower watermark is released (close/promotion);
        # an in-flight sync round must not re-pin the shared log after it
        self._lw_released = False
        # Pipelined ingest: parallel per-SST flush encode pool width, the
        # optional write-buffer freeze hook (set by the engine when
        # ingest.flush_overlap is on — flush moves the frozen memtable's
        # bytes out of the mutable budget so writes keep flowing during
        # the encode), and the last write's per-stage wall (wal/memtable
        # ms — the write.region span attrs; single-writer-per-region makes
        # the unlocked read safe).
        # clamp to REAL cores: on a 1-core box the pool (and the window
        # slicing keyed off it) is pure overhead — more files, more index
        # builds, zero parallelism
        try:
            cores = len(os.sched_getaffinity(0))
        except AttributeError:  # non-linux
            cores = os.cpu_count() or 1
        self.flush_workers = max(1, min(flush_workers, cores))
        self.buffer_mgr = None
        self.last_write_stage_ms: dict = {}
        self._conform_cache: tuple | None = None
        self._replay_wal()

    # ---- open/replay ------------------------------------------------------
    def _replay_wal(self):
        """Replay WAL entries newer than flushed_entry_id into the memtable."""
        flushed = self.manifest_mgr.manifest.flushed_entry_id
        truncated = self.manifest_mgr.manifest.truncated_entry_id or 0
        start = max(flushed, truncated)
        last = start
        replayed = 0
        for entry in self.wal.replay(start):
            self.sequence += 1
            self.memtable.write(self._conform(entry.batch), self.sequence)
            last = entry.entry_id
            replayed += entry.batch.num_rows
        self.applied_entry_id = last
        return replayed

    # ---- write ------------------------------------------------------------
    def write(self, batch: pa.RecordBatch) -> int:
        """WAL append then memtable insert; returns affected rows."""
        with self._lock:
            # the writable check lives INSIDE the lock: set_writable(False)
            # (migration downgrade) takes the same lock, so once the fence
            # returns, no in-flight write can still append to the WAL the
            # migration candidate is about to replay
            if not self.writable:
                raise RegionReadonlyError(f"region {self.region_id} is read-only")
            batch = self._conform(batch)
            t0 = time.perf_counter()
            self.wal.append(batch)
            t1 = time.perf_counter()
            self.sequence += 1
            self.memtable.write(batch, self.sequence)
            t2 = time.perf_counter()
            self.applied_entry_id = self.wal.last_entry_id
        wal_ms, mem_ms = (t1 - t0) * 1000, (t2 - t1) * 1000
        self.last_write_stage_ms = {"wal": wal_ms, "memtable": mem_ms}
        metrics.INGEST_WAL_MS.observe(wal_ms)
        metrics.INGEST_MEMTABLE_MS.observe(mem_ms)
        metrics.INGEST_WRITES_TOTAL.inc()
        metrics.WRITE_ROWS_TOTAL.inc(batch.num_rows)
        return batch.num_rows

    def write_group(self, batches: list[pa.RecordBatch]) -> list[int]:
        """Group commit (ingest.group_commit): one WAL frame for a whole
        region-worker drain group, one entry id AND one sequence per write
        — live state equals a crash replay of the same frame entry for
        entry.  Returns per-write affected row counts in order."""
        from ..utils import fault_injection

        if not batches:
            return []
        with self._lock:
            if not self.writable:
                raise RegionReadonlyError(f"region {self.region_id} is read-only")
            fault_injection.fire(
                "ingest.group_commit", region_id=self.region_id, n=len(batches)
            )
            conformed = [self._conform(b) for b in batches]
            t0 = time.perf_counter()
            append_group = getattr(self.wal, "append_group", None)
            if append_group is not None:
                append_group(conformed)
            else:  # a WAL impl without group frames: per-write appends
                for b in conformed:
                    self.wal.append(b)
            t1 = time.perf_counter()
            # one sequence per write, exactly like replay assigns them
            for b in conformed:
                self.sequence += 1
                self.memtable.write(b, self.sequence)
            t2 = time.perf_counter()
            self.applied_entry_id = self.wal.last_entry_id
        wal_ms, mem_ms = (t1 - t0) * 1000, (t2 - t1) * 1000
        self.last_write_stage_ms = {
            "wal": wal_ms, "memtable": mem_ms, "group": len(batches),
        }
        metrics.INGEST_WAL_MS.observe(wal_ms)
        metrics.INGEST_MEMTABLE_MS.observe(mem_ms)
        metrics.INGEST_WRITES_TOTAL.inc(len(batches))
        rows = [b.num_rows for b in conformed]
        metrics.WRITE_ROWS_TOTAL.inc(sum(rows))
        return rows

    def _conform(self, batch: pa.RecordBatch) -> pa.RecordBatch:
        """Project a write onto the region's current schema (+ the __op
        marker): a batch built against an older (narrower) schema gets nulls
        for columns added by a concurrent ALTER, puts without a marker get
        __op=0, and columns come out in schema order so every memtable chunk
        shares one schema (the reference's write-compat shim,
        mito2/src/read/compat.rs, does this on read instead)."""
        cache = self._conform_cache
        if cache is None or cache[0] is not self.schema:
            # keyed on schema object identity: ALTER/manifest refresh swap
            # the Schema instance, invalidating the cached Arrow target
            target = self.schema.to_arrow().append(pa.field(OP_COL, pa.int8()))
            self._conform_cache = (self.schema, target)
        else:
            target = cache[1]
        if batch.schema.equals(target):
            return batch
        n = batch.num_rows
        arrays = []
        for f in target:
            i = batch.schema.get_field_index(f.name)
            if i >= 0:
                col = batch.column(i)
                arrays.append(col if col.type == f.type else col.cast(f.type))
            elif f.name == OP_COL:
                arrays.append(pa.array(np.zeros(n, dtype=np.int8)))
            else:
                arrays.append(pa.nulls(n, f.type))
        return pa.RecordBatch.from_arrays(arrays, schema=target)

    def delete(self, keys: pa.Table | pa.RecordBatch) -> int:
        """Delete by key: `keys` carries the primary-key + time-index columns
        of the rows to remove.  Writes tombstone rows (__op=1) through the
        normal WAL/memtable path — _conform null-fills the field columns —
        and dedup hides the victims immediately (reference mito2 handles
        OpType::Delete the same way)."""
        if self.append_mode:
            from ..utils.errors import UnsupportedError

            raise UnsupportedError("DELETE is not supported on append_mode tables")
        if isinstance(keys, pa.Table):
            keys = keys.combine_chunks()
            batches = keys.to_batches()
        else:
            batches = [keys]
        deleted = 0
        for b in batches:
            if b.num_rows == 0:
                continue
            op = pa.array(np.full(b.num_rows, OP_DELETE, dtype=np.int8))
            self.write(b.append_column(pa.field(OP_COL, pa.int8()), op))
            deleted += b.num_rows
        return deleted

    # ---- flush ------------------------------------------------------------
    def flush(self) -> list[FileMeta]:
        """Freeze the memtable, write one SST per time window, commit the
        manifest edit, truncate WAL.  The frozen memtable stays scannable
        (in _frozen_memtables) until the manifest edit lands, so concurrent
        scans never see the flush-in-progress rows vanish."""
        with self._lock:
            if self.memtable.is_empty():
                return []
            frozen = self.memtable
            frozen_bytes = frozen.memory_usage
            frozen_entry_id = self.wal.last_entry_id
            frozen_sequence = self.sequence
            self.memtable = make_memtable(self.schema, self.time_partition_ms, self.memtable_kind)
            self._frozen_memtables.append(frozen)
            if self.buffer_mgr is not None:
                # flush overlap (ingest.flush_overlap): the frozen bytes
                # leave the MUTABLE budget now, so new writes are admitted
                # while this encode runs; the flushing bucket keeps the
                # total bounded (see WriteBufferManager.should_stall)
                self.buffer_mgr.freeze_region(self.region_id, frozen_bytes)
        t0 = time.perf_counter()
        try:
            added = self._encode_sst_windows(frozen)
        finally:
            if self.buffer_mgr is not None:
                self.buffer_mgr.unfreeze_region(self.region_id, frozen_bytes)
        metrics.INGEST_FLUSH_ENCODE_MS.observe((time.perf_counter() - t0) * 1000)
        with self._lock:
            truncated = self.manifest_mgr.manifest.truncated_entry_id or 0
            if truncated >= frozen_entry_id:
                # a TRUNCATE landed while the SSTs were being written: the
                # frozen rows are logically gone — discard the files instead
                # of committing them (the reference versions flushes against
                # the truncate watermark the same way)
                if frozen in self._frozen_memtables:
                    self._frozen_memtables.remove(frozen)
                self._garbage_files.extend(
                (m.file_id, time.time()) for m in added
            )
                self._purge_garbage_locked()
                return []
            self.manifest_mgr.apply(
                {
                    "kind": "edit",
                    "files_to_add": [m.to_dict() for m in added],
                    "files_to_remove": [],
                    "flushed_entry_id": frozen_entry_id,
                    "flushed_sequence": frozen_sequence,
                }
            )
            self._frozen_memtables.remove(frozen)
        self.wal.obsolete(frozen_entry_id)
        metrics.FLUSH_TOTAL.inc()
        metrics.FLUSH_ELAPSED.observe(time.perf_counter() - t0)
        return added

    # Rows per SST slice when one time window dominates a flush: a
    # window's sorted run splits into consecutive slices (disjoint key
    # ranges by construction) so the encode pool has work even when the
    # whole flush lands in ONE window (the TSBS shape: days-wide
    # partitions, minutes-wide flushes).
    _FLUSH_SLICE_ROWS = 1 << 20

    def _encode_sst_windows(self, frozen: Memtable) -> list[FileMeta]:
        """Encode the frozen memtable's time windows into SSTs — in
        parallel over `flush_workers` (ingest.flush_workers; Parquet
        encode and index builds release the GIL, so the pool overlaps
        real work).  Big single-window flushes slice their sorted run
        into consecutive ~1M-row SSTs: slices of a sorted table cover
        disjoint (pk, ts) ranges, so downstream merge/dedup treats them
        exactly like any other L0 run split.  Output order stays window
        order (slices in run order), so manifest positions are
        deterministic."""
        parts = frozen.split_by_time_partition(
            # last_non_null must NOT last-row-dedup on flush: older
            # versions' non-null fields are still live until the READ-side
            # fieldwise merge combines them
            dedup=not self.append_mode and self.merge_mode != "last_non_null"
        )
        tables: list[pa.Table] = []
        for _w, t in parts:
            if (self.flush_workers > 1
                    and t.num_rows > 2 * self._FLUSH_SLICE_ROWS):
                step = self._FLUSH_SLICE_ROWS
                tables.extend(
                    t.slice(off, step) for off in range(0, t.num_rows, step)
                )
            else:
                tables.append(t)
        if self.flush_workers > 1 and len(tables) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(self.flush_workers, len(tables)),
                thread_name_prefix=f"flush-encode-{self.region_id}",
            ) as ex:
                metas = list(ex.map(
                    lambda t: self.sst_writer.write(t, level=0), tables
                ))
        else:
            metas = [self.sst_writer.write(t, level=0) for t in tables]
        return [m for m in metas if m is not None]

    # ---- compaction hook (files swapped by CompactionScheduler) -----------
    def apply_compaction(
        self, files_to_add: list[FileMeta], files_to_remove: list[str]
    ) -> bool:
        """Commit a compaction edit.  The output is INSERTED at the newest
        input's manifest position (not appended): flushes landing DURING
        the merge stay newer, so last-write-wins order — which scans judge
        by manifest position — survives concurrent overwrites.  Returns
        False (caller discards the output) when the commit would be
        unsound: an input vanished, or a file outside the group that
        time-overlaps an input sits BETWEEN input positions — one output
        position cannot rank above its older inputs yet below such an
        interleaved outsider (the reference dedups by persisted per-row
        sequences instead; mito2/src/read/dedup.rs)."""
        with self._lock:
            order = list(self.manifest_mgr.manifest.files)
            pos = {fid: i for i, fid in enumerate(order)}
            metas = self.manifest_mgr.manifest.files
            in_pos = sorted(
                pos[fid] for fid in files_to_remove if fid in pos
            )
            if len(in_pos) != len(files_to_remove):
                return False  # an input left the manifest mid-merge
            anchor = order[in_pos[-1]] if in_pos else None
            if not self.append_mode and len(in_pos) > 1:
                from .sst import interleaved_overlap_unsafe

                inputs = [metas[fid] for fid in files_to_remove]
                if interleaved_overlap_unsafe(
                    inputs, list(metas.values()), pos
                ):
                    return False
            self.manifest_mgr.apply(
                {
                    "kind": "edit",
                    "files_to_add": [m.to_dict() for m in files_to_add],
                    "files_to_remove": files_to_remove,
                    "insert_at": anchor,
                }
            )
            # Defer physical deletion: in-flight scans may hold the old file
            # list (the reference defers via a file purger + refcounts).
            self._garbage_files.extend(
                (fid, time.time()) for fid in files_to_remove
            )
            self._purge_garbage_locked()
        metrics.COMPACTION_TOTAL.inc()
        return True

    def _purge_garbage_locked(self):
        if self._active_scans > 0 or not self._garbage_files:
            return
        now = time.time()
        keep: list[tuple[str, float]] = []
        for fid, t0 in self._garbage_files:
            if now - t0 >= self.gc_grace_secs:
                self.sst_reader.delete(fid)
            else:
                keep.append((fid, t0))
        self._garbage_files = keep

    # ---- read -------------------------------------------------------------
    def scan(
        self,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
    ) -> pa.Table:
        """Snapshot scan: SSTs (pruned) + frozen + active memtables, dedup
        last-write-wins across sources.  Memtable rows shadow SST rows for
        equal (pk, ts) because they carry later sequences."""
        pred = pred or ScanPredicate()
        with self._lock:
            files = list(self.manifest_mgr.manifest.files.values())
            mems = list(self._frozen_memtables) + [self.memtable]
            self._active_scans += 1
        try:
            # Filters on key columns (tags + time index) are dedup-safe for
            # pruning/pre-filtering: a newer version of a row (overwrite or
            # tombstone) has the same key, so both versions pass or fail
            # together.  Filters on FIELD columns must wait until after
            # cross-source dedup — a stale SST row could pass a field filter
            # while its memtable replacement (new value / tombstone with null
            # fields) fails it, resurrecting overwritten data (the reference
            # orders DedupReader before filter eval the same way).
            key_cols = set(c.name for c in self.schema.tag_columns())
            if self.schema.time_index is not None:
                key_cols.add(self.schema.time_index.name)
            key_filters = [f for f in pred.filters if f[0] in key_cols]
            post_filters = [f for f in pred.filters if f[0] not in key_cols]
            # append_mode has no dedup, so FIELD filters (incl. fulltext
            # match) may prune files/segments too — dropping a non-matching
            # row can never resurrect an older version when versions don't
            # shadow each other (the logs fast path: matches() + fulltext
            # index pruning before any Parquet decode)
            prune_filters = list(pred.filters) if self.append_mode else key_filters
            prune_pred = ScanPredicate(time_range=pred.time_range, filters=prune_filters)

            # Projection pushdown: read only requested columns plus the
            # pk/ts/__op columns dedup needs; final select() trims extras.
            read_cols = None
            if columns:
                need = list(dict.fromkeys(columns))
                for c in self.schema.primary_key():
                    if c not in need:
                        need.append(c)
                if self.schema.time_index and self.schema.time_index.name not in need:
                    need.append(self.schema.time_index.name)
                for name, _op, _v in pred.filters:
                    if self.schema.has_column(name) and name not in need:
                        need.append(name)
                need.append(OP_COL)
                read_cols = need
            tables = []
            for meta in self.sst_reader.prune_files(files, prune_pred):
                check_deadline()
                t = self.sst_reader.read(meta, prune_pred, columns=read_cols)
                if t.num_rows:
                    tables.append(self._compat_cast(_undict(t)))
            n_sst_tables = len(tables)
            from .sst import _apply_residual

            ts_name = self.schema.time_index.name if self.schema.time_index else None
            mem_rows = 0
            keep_versions = self.merge_mode == "last_non_null"
            for mem in mems:
                mem_table = mem.scan(
                    pred.time_range,
                    dedup=not self.append_mode and not keep_versions,
                )
                if mem_table.num_rows:
                    mem_table = _apply_residual(mem_table, prune_pred, ts_name)
                if mem_table.num_rows:
                    if read_cols:
                        mem_table = mem_table.select(
                            [c for c in read_cols if c in mem_table.column_names]
                        )
                    mem_rows += mem_table.num_rows
                    tables.append(_undict(mem_table))
            if not tables:
                out = self.schema.to_arrow().empty_table()
            else:
                out = pa.concat_tables(tables, promote_options="permissive")
                out = self._dedup_across_sources(
                    out,
                    had_multiple=len(tables) > 1
                    or (n_sst_tables and mem_rows)
                    or self.merge_mode == "last_non_null",
                )
                out = self._drop_tombstones(out)
                if post_filters:
                    out = _apply_residual(
                        out, ScanPredicate(filters=post_filters), None
                    )
            # schema evolution: columns added by ALTER after this data was
            # written materialize as NULL (reference mito2/src/read/compat.rs
            # fills missing columns with default vectors at read)
            for c in self.schema.columns:
                if c.name not in out.column_names:
                    out = out.append_column(
                        c.name, pa.nulls(out.num_rows, c.data_type.to_arrow())
                    )
            if columns:
                out = out.select([c for c in columns if c in out.column_names])
            else:
                # normalize to the CURRENT schema: old SSTs may still carry
                # columns dropped by ALTER
                want = [c for c in self.schema.column_names() if c in out.column_names]
                if want != out.column_names:
                    out = out.select(want)
            return out
        finally:
            with self._lock:
                self._active_scans -= 1
                self._purge_garbage_locked()

    def scan_windows(
        self,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
        window_ms: int | None = None,
        governor=None,
    ):
        """Bounded-memory streaming scan: yield one time window at a time.

        The reference streams via PartitionRanges (mito2/src/read/range.rs +
        seq_scan.rs); here the partition unit is the memtable time-partition
        window.  Correctness: dedup keys include the time index, so a
        (pk, ts) duplicate lives in exactly ONE window — per-window
        sort+dedup equals the global pass.  Peak memory is one window's
        rows, admitted against `governor.scan_guard` when provided."""
        pred = pred or ScanPredicate()
        w = window_ms or self.time_partition_ms
        with self._lock:
            files = list(self.manifest_mgr.manifest.files.values())
            mems = list(self._frozen_memtables) + [self.memtable]
            self._active_scans += 1
        try:
            # window set from file metas + memtable ranges, intersected with
            # the predicate's time range
            starts: set[int] = set()
            lo_q, hi_q = pred.time_range if pred.time_range else (None, None)

            def add_range(lo, hi):
                lo = lo if lo_q is None else max(lo, lo_q)
                hi = hi if hi_q is None else min(hi, hi_q - 1)
                if hi < lo:
                    return
                s = (lo // w) * w
                while s <= hi:
                    starts.add(s)
                    s += w
            for fm in files:
                add_range(*fm.time_range)
            for mem in mems:
                r = mem.time_range()
                if r is not None:
                    add_range(*r)
            if self.schema.time_index is None:
                # no time index: single-shot fallback
                yield self.scan(pred, columns)
                return
            for s in sorted(starts):
                win_pred = ScanPredicate(
                    time_range=(
                        max(s, lo_q) if lo_q is not None else s,
                        min(s + w, hi_q) if hi_q is not None else s + w,
                    ),
                    filters=pred.filters,
                )
                chunk = self.scan(win_pred, columns)
                if chunk.num_rows == 0:
                    continue
                if governor is not None:
                    with governor.scan_guard(chunk.nbytes):
                        yield chunk
                else:
                    yield chunk
        finally:
            with self._lock:
                self._active_scans -= 1
                self._purge_garbage_locked()

    def _compat_cast(self, table: pa.Table) -> pa.Table:
        """Adapt an old SST to the CURRENT schema (reference
        mito2/src/read/compat.rs): cast columns to the declared type after
        ALTER ... MODIFY COLUMN, and null out name-collisions whose stored
        column_id differs — data of a DROPped column must not resurrect when
        a new column reuses its name."""
        import pyarrow.compute as pc

        for col in self.schema.columns:
            i = table.schema.get_field_index(col.name)
            if i < 0:
                continue
            fmeta = table.schema.field(i).metadata or {}
            stored_id = int(fmeta.get(b"greptime:column_id", 0))
            want = col.data_type.to_arrow()
            if stored_id and col.column_id and stored_id != col.column_id:
                table = table.set_column(
                    i, col.to_arrow(), pa.nulls(table.num_rows, want)
                )
            elif table.schema.field(i).type != want:
                table = table.set_column(
                    i, col.name, pc.cast(table.column(i), want)
                )
        return table

    @staticmethod
    def _drop_tombstones(table: pa.Table) -> pa.Table:
        """Remove delete markers from scan output (rows from pre-__op files
        have a null marker and count as puts)."""
        if OP_COL not in table.column_names:
            return table
        import pyarrow.compute as pc

        op = pc.fill_null(pc.cast(table[OP_COL], pa.int8()), OP_PUT)
        table = table.filter(pc.equal(op, OP_PUT))
        return table.drop_columns([OP_COL])

    def _dedup_across_sources(self, table: pa.Table, had_multiple: bool) -> pa.Table:
        if not had_multiple or table.num_rows <= 1:
            return table
        # Order sources oldest->newest (SSTs then memtable appended last);
        # reuse memtable sort+dedup with the append order as sequence.
        # append_mode keeps duplicates but still sorts by (pk, ts) so
        # downstream consumers (PromQL, range kernels) see ordered series.
        import numpy as np

        if self.merge_mode == "last_non_null" and not self.append_mode:
            from .merge import _SEQ, _dedup_chunk

            key_cols = [c.name for c in self.schema.tag_columns()]
            if self.schema.time_index is not None:
                key_cols.append(self.schema.time_index.name)
            seq = pa.array(np.arange(table.num_rows, dtype=np.int64))
            table = table.append_column(_SEQ, seq)
            return _dedup_chunk(table, key_cols, self.schema, True, "last_non_null")

        from .memtable import _SEQ_COL, _sort_and_dedup

        seq = pa.array(np.arange(table.num_rows, dtype=np.int64))
        table = table.append_column(_SEQ_COL, seq)
        table = _sort_and_dedup(table, self.schema, dedup=not self.append_mode)
        return table.drop_columns([_SEQ_COL])

    def scan_merge_stream(
        self,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
        batch_rows: int = 65536,
    ):
        """Streaming scan: per-source sorted batches merged through a
        k-way run-cutting merger with mode-aware dedup (reference
        mito2/src/read/merge.rs MergeReader + dedup.rs DedupReader).
        Peak memory is O(batch + one row group per source) instead of the
        whole scan; SSTs stream row-group-at-a-time."""
        import numpy as np

        from .merge import _SEQ, merge_sorted
        from .sst import _apply_residual

        pred = pred or ScanPredicate()
        with self._lock:
            files = list(self.manifest_mgr.manifest.files.values())
            mems = list(self._frozen_memtables) + [self.memtable]
            self._active_scans += 1
        try:
            key_cols = {c.name for c in self.schema.tag_columns()}
            if self.schema.time_index is not None:
                key_cols.add(self.schema.time_index.name)
            key_filters = [f for f in pred.filters if f[0] in key_cols]
            post_filters = [f for f in pred.filters if f[0] not in key_cols]
            prune_pred = ScanPredicate(
                time_range=pred.time_range,
                filters=list(pred.filters) if self.append_mode else key_filters,
            )
            read_cols = None
            if columns:
                need = list(dict.fromkeys(columns))
                for c in self.schema.primary_key():
                    if c not in need:
                        need.append(c)
                if self.schema.time_index and self.schema.time_index.name not in need:
                    need.append(self.schema.time_index.name)
                for name, _op, _v in pred.filters:
                    if self.schema.has_column(name) and name not in need:
                        need.append(name)
                need.append(OP_COL)
                read_cols = need
            base = 0

            def sst_source(meta, base_seq):
                for t in self.sst_reader.read_batches(
                    meta, prune_pred, columns=read_cols
                ):
                    t = self._compat_cast(_undict(t))
                    seq = pa.array(
                        base_seq + np.arange(t.num_rows, dtype=np.int64)
                    )
                    yield t.append_column(_SEQ, seq)

            def mem_source(mem, base_seq):
                t = mem.scan(pred.time_range, dedup=False)
                if t.num_rows:
                    t = _apply_residual(t, prune_pred, None)
                if t.num_rows and read_cols:
                    t = t.select([c for c in read_cols if c in t.column_names])
                if t.num_rows:
                    seq = pa.array(
                        base_seq + np.arange(t.num_rows, dtype=np.int64)
                    )
                    yield _undict(t).append_column(_SEQ, seq)

            sources = []
            for meta in self.sst_reader.prune_files(files, prune_pred):
                sources.append(sst_source(meta, base))
                base += 1 << 40
            for mem in mems:
                sources.append(mem_source(mem, base))
                base += 1 << 40
            ts_name = (
                self.schema.time_index.name if self.schema.time_index else None
            )
            for out in merge_sorted(
                sources,
                self.schema,
                dedup=not self.append_mode,
                mode=self.merge_mode,
                batch_rows=batch_rows,
            ):
                out = self._drop_tombstones(out)
                if post_filters:
                    out = _apply_residual(
                        out, ScanPredicate(filters=post_filters), None
                    )
                # schema evolution: late columns read as NULL
                for c in self.schema.columns:
                    if c.name not in out.column_names:
                        out = out.append_column(
                            c.name, pa.nulls(out.num_rows, c.data_type.to_arrow())
                        )
                if columns:
                    out = out.select(
                        [c for c in columns if c in out.column_names]
                    )
                else:
                    want = [
                        c for c in self.schema.column_names()
                        if c in out.column_names
                    ]
                    if want != out.column_names:
                        out = out.select(want)
                if out.num_rows:
                    yield out
        finally:
            with self._lock:
                self._active_scans -= 1
                self._purge_garbage_locked()

    # ---- tile-cache support ------------------------------------------------
    def pin_scan(self):
        """Hold the deferred-purge refcount open while the device tile cache
        reads SST files outside `scan()` (same protection in-flight scans
        get: compaction must not delete files under us)."""
        with self._lock:
            self._active_scans += 1

    def unpin_scan(self):
        with self._lock:
            self._active_scans -= 1
            self._purge_garbage_locked()

    def approx_rows(self) -> int:
        """Cheap row-count estimate (manifest stats + memtables) for the
        query planner's layout/cost decisions — the role of the
        reference's region statistics (store-api region_statistic)."""
        with self._lock:
            rows = sum(
                m.num_rows for m in self.manifest_mgr.manifest.files.values()
            )
            rows += self.memtable.num_rows
            rows += sum(m.num_rows for m in self._frozen_memtables)
        return rows

    def distinct_estimate(self, column: str) -> int | None:
        """Upper-bound distinct-value estimate for `column` from the
        per-SST segmented term index metas (one small cached ranged read
        per file): the sum of per-file term counts over-counts values
        shared across files, which is the safe direction for sizing a
        hash table.  None when no file carries a segmented index for the
        column (the planner falls back to dictionary cardinality)."""
        with self._lock:
            files = list(self.manifest_mgr.manifest.files.values())
        total = None
        for meta in files:
            if column not in meta.indexed_columns:
                continue
            n = self.sst_reader.distinct_terms(meta, column)
            if n is not None:
                total = n if total is None else total + n
        return total

    def tile_snapshot(self) -> tuple[list[FileMeta], list[Memtable], int]:
        """Consistent (files, memtables, manifest_version) snapshot for the
        tile executor.  Caller must hold pin_scan() around use."""
        with self._lock:
            files = list(self.manifest_mgr.manifest.files.values())
            mems = list(self._frozen_memtables) + [self.memtable]
            version = self.manifest_mgr.manifest.manifest_version
        return files, mems, version

    # ---- admin ------------------------------------------------------------
    def truncate(self):
        with self._lock:
            entry_id = self.wal.last_entry_id
            dropped = list(self.manifest_mgr.manifest.files)
            self.manifest_mgr.apply({"kind": "truncate", "truncated_entry_id": entry_id})
            self.memtable = make_memtable(self.schema, self.time_partition_ms, self.memtable_kind)
            # frozen memtables hold pre-truncate rows an in-flight flush froze;
            # drop them so scans stop seeing truncated data immediately (the
            # flush itself discards its SSTs when it observes the watermark)
            self._frozen_memtables.clear()
            self.wal.obsolete(entry_id)
            # the truncated SSTs are unreferenced now; reclaim them once
            # in-flight scans drain (same deferred purge as compaction)
            self._garbage_files.extend((fid, time.time()) for fid in dropped)
            self._purge_garbage_locked()

    def alter_schema(self, new_schema: Schema):
        """Schema change: flush first so existing SSTs stay self-describing."""
        with self._lock:
            self.flush()
            self.manifest_mgr.apply({"kind": "change", "schema": new_schema.to_json()})
            self.schema = new_schema
            self.sst_writer.schema = new_schema
            self.sst_reader.schema = new_schema
            self.memtable = make_memtable(new_schema, self.time_partition_ms, self.memtable_kind)

    # ---- follower freshness (bounded-staleness replicas) ------------------
    def follower_sync(self) -> tuple[int, bool]:
        """One freshness round for a READ-ONLY follower region: refresh the
        manifest view when the leader's version advanced (flush/compaction/
        truncate/alter — compaction-deleted SSTs drop out of the file list
        before a hedged read trips over them), then replay the shared-WAL
        tail past `applied_entry_id` into the memtable.  Returns
        (entries_applied, manifest_refreshed).

        Correctness of the refresh path: adopting a fresh manifest resets
        the memtable and restarts the tail from the NEW flushed watermark —
        rows the leader flushed are now served from the refreshed SST set,
        rows it has not are still in the log above the watermark, so the
        follower view equals what a fresh open would build, without the
        open cost.  A leader never runs this (writable regions return
        immediately), so the snapshot behavior with syncing disabled is
        bit-for-bit the pre-freshness one."""
        from ..utils import fault_injection

        fault_injection.fire("replica.sync", region_id=self.region_id)
        with self._lock:
            if self.writable:
                return 0, False
            applied, refreshed = self._catch_up_locked()
            applied_to = self.applied_entry_id
        # register the replay low-watermark OUTSIDE the region lock (it
        # writes a shared file); shared-WAL prune keeps everything above it
        register = getattr(self.wal, "register_replay_position", None)
        if register is not None:
            register(applied_to)
            # close_region/promotion may have released the watermark while
            # the registration write was in flight — a released region must
            # never be re-pinned by a stale sync round (the orphan would
            # hold pruning back for the whole registration TTL)
            with self._lock:
                released = self._lw_released
            if released:
                self.release_follower_watermark()
        label = str(self.region_id)
        metrics.FOLLOWER_SYNC_TOTAL.inc()
        metrics.FOLLOWER_LAG_ENTRIES.set(0.0, region=label)
        metrics.FOLLOWER_LAG_MS.set(0.0, region=label)
        return applied, refreshed

    def _catch_up_locked(self) -> tuple[int, bool]:
        """Adopt the leader's manifest state if it advanced, then replay the
        log tail past `applied_entry_id` into the memtable.  Shared by the
        follower sync round and the promotion path (`set_writable(True)`).
        Returns (entries_applied, manifest_refreshed)."""
        manifest, refreshed = self.manifest_mgr.refresh()
        if refreshed:
            metrics.FOLLOWER_MANIFEST_REFRESH_TOTAL.inc()
            if manifest.schema is not None:
                self.schema = manifest.schema
                self.sst_writer.schema = manifest.schema
                self.sst_reader.schema = manifest.schema
            self.memtable = make_memtable(
                self.schema, self.time_partition_ms, self.memtable_kind
            )
            self._frozen_memtables.clear()
            self.sequence = manifest.flushed_sequence
            self.applied_entry_id = max(
                manifest.flushed_entry_id, manifest.truncated_entry_id or 0
            )
        applied = 0
        for entry in self.wal.replay(self.applied_entry_id):
            self.sequence += 1
            self.memtable.write(self._conform(entry.batch), self.sequence)
            self.applied_entry_id = entry.entry_id
            applied += 1
        self.wal.advance_to(self.applied_entry_id)
        self.last_sync_ms = time.time() * 1000
        return applied, refreshed

    def release_follower_watermark(self):
        """Stop holding the shared WAL back (follower closed/promoted).
        Latches `_lw_released` so an in-flight sync round that registers
        concurrently undoes its own registration (see follower_sync)."""
        with self._lock:
            self._lw_released = True
        release = getattr(self.wal, "release_replay_position", None)
        if release is not None:
            release()

    def set_writable(self, writable: bool):
        """Leader/follower role flip (reference set_region_role).  Takes
        the region lock so a downgrade returns only after in-flight writes
        finish their WAL append — the migration candidate's catch-up replay
        must never race a torn tail."""
        with self._lock:
            was = self.writable
            if writable and not was:
                # promotion catch-up: adopt the leader's final manifest
                # state and replay the un-applied shared-log tail BEFORE
                # the first write — entries above the last sync round would
                # otherwise be lost from the memtable, and the first append
                # would reuse entry ids the old leader already wrote to the
                # shared topic (append allocates last_entry_id + 1)
                self._catch_up_locked()
            if not writable:
                # (re)entering the follower role: sync rounds may pin the
                # shared log again (a later promotion re-latches)
                self._lw_released = False
            self.writable = writable
        if writable and not was:
            # a promoted follower must not keep pinning the shared log
            self.release_follower_watermark()

    def stat(self) -> RegionStat:
        m = self.manifest_mgr.manifest
        lag_entries, lag_ms = 0, 0.0
        if not self.writable:
            lag_entries = max(0, self.wal.last_entry_id - self.applied_entry_id)
            lag_ms = max(0.0, time.time() * 1000 - self.last_sync_ms)
            label = str(self.region_id)
            metrics.FOLLOWER_LAG_ENTRIES.set(lag_entries, region=label)
            metrics.FOLLOWER_LAG_MS.set(lag_ms, region=label)
        return RegionStat(
            region_id=self.region_id,
            num_rows=sum(f.num_rows for f in m.files.values()) + self.memtable.num_rows,
            sst_count=len(m.files),
            sst_bytes=sum(f.file_size for f in m.files.values()),
            memtable_bytes=self.memtable.memory_usage,
            wal_entry_id=self.wal.last_entry_id,
            flushed_entry_id=m.flushed_entry_id,
            writable=self.writable,
            follower_lag_entries=lag_entries,
            follower_lag_ms=lag_ms,
        )

    def files(self) -> list[FileMeta]:
        with self._lock:
            return list(self.manifest_mgr.manifest.files.values())

    def read_sst(self, meta: FileMeta, pred: ScanPredicate | None = None) -> pa.Table:
        return _undict(self.sst_reader.read(meta, pred))


def _undict(table: pa.Table) -> pa.Table:
    """Decode dictionary columns back to plain values for cross-file concat."""
    import pyarrow.compute as pc

    for i, f in enumerate(table.schema):
        if pa.types.is_dictionary(f.type):
            table = table.set_column(i, f.name, pc.cast(table[f.name], f.type.value_type))
    return table
