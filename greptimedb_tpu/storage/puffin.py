"""Puffin blob container for per-SST index data.

Role-equivalent of the reference's `puffin` crate (reference
puffin/src/puffin_manager.rs, file_format/): the Apache-Iceberg-Puffin
file layout — magic, concatenated blobs, JSON footer describing blob
offsets/types/properties, footer length, flags, trailing magic — used as
the single sidecar file holding all of an SST's secondary indexes.

Layout (matches the Puffin spec structure):

    "PFA1" | blob_0 | blob_1 | ... | footer_json | footer_len(u32 LE) |
    flags(u32 LE) | "PFA1"
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass, field

MAGIC = b"PFA1"


@dataclass
class BlobMeta:
    blob_type: str  # e.g. "greptime-bloom-filter-v1", "greptime-inverted-index-v1"
    offset: int
    length: int
    properties: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "type": self.blob_type,
            "offset": self.offset,
            "length": self.length,
            "properties": self.properties,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BlobMeta":
        return cls(d["type"], d["offset"], d["length"], d.get("properties", {}))


def _as_store(store_or_path, key: str | None):
    """(store, key) pair from either an ObjectStore+key or a bare fs path
    (legacy call shape: PuffinWriter('/dir/x.puffin'))."""
    from .object_store import FsObjectStore, ObjectStore

    if isinstance(store_or_path, ObjectStore):
        assert key is not None, "key required with an ObjectStore"
        return store_or_path, key
    path = store_or_path
    return FsObjectStore(os.path.dirname(path) or "."), os.path.basename(path)


class PuffinWriter:
    def __init__(self, store_or_path, key: str | None = None):
        self.store, self.key = _as_store(store_or_path, key)
        self._blobs: list[tuple[BlobMeta, bytes]] = []

    def add_blob(self, blob_type: str, data: bytes, properties: dict | None = None):
        self._blobs.append((BlobMeta(blob_type, 0, len(data), properties or {}), data))

    def finish(self) -> int:
        """Write the container; returns file size. No file if no blobs."""
        if not self._blobs:
            return 0
        parts = [MAGIC]
        off = len(MAGIC)
        metas = []
        for meta, data in self._blobs:
            meta.offset = off
            parts.append(data)
            off += len(data)
            metas.append(meta.to_dict())
        footer = json.dumps({"blobs": metas}).encode()
        parts.append(footer)
        parts.append(struct.pack("<I", len(footer)))
        parts.append(struct.pack("<I", 0))  # flags
        parts.append(MAGIC)
        payload = b"".join(parts)
        self.store.write(self.key, payload)
        return len(payload)


class PuffinReader:
    """`ranged=False` (default) reads the whole container once and slices —
    right for small sidecars consumed blob-by-blob.  `ranged=True` reads
    the footer via a tail range and each blob via its own ranged read, so
    touching ONE blob of a large container (a segmented term index with
    thousands of segment blobs) costs O(blob), not O(file); `bytes_read`
    accumulates the ranged bytes actually fetched for observability."""

    def __init__(self, store_or_path, key: str | None = None, ranged: bool = False):
        self.store, self.key = _as_store(store_or_path, key)
        self.ranged = ranged
        self.bytes_read = 0
        self._metas: list[BlobMeta] | None = None
        self._data: bytes | None = None

    def exists(self) -> bool:
        return self.store.exists(self.key)

    def _payload(self) -> bytes:
        # Legacy whole-blob sidecars are small (bounded by cardinality
        # caps); one read beats three for every blob on a remote store.
        if self._data is None:
            self._data = self.store.read(self.key)
        return self._data

    def blobs(self) -> list[BlobMeta]:
        if self._metas is None:
            if self.ranged:
                size = self.store.size(self.key)
                tail = self.store.read_range(self.key, max(size - 12, 0), 12)
                self.bytes_read += len(tail)
                footer_len = struct.unpack("<I", tail[:4])[0]
                if tail[8:] != MAGIC:
                    raise ValueError(f"bad puffin trailer in {self.key}")
                footer_raw = self.store.read_range(
                    self.key, size - 12 - footer_len, footer_len
                )
                self.bytes_read += len(footer_raw)
                footer = json.loads(footer_raw)
            else:
                data = self._payload()
                if data[:4] != MAGIC:
                    raise ValueError(f"bad puffin magic in {self.key}")
                tail = data[-12:]
                footer_len = struct.unpack("<I", tail[:4])[0]
                if tail[8:] != MAGIC:
                    raise ValueError(f"bad puffin trailer in {self.key}")
                footer = json.loads(data[len(data) - 12 - footer_len : len(data) - 12])
            self._metas = [BlobMeta.from_dict(d) for d in footer["blobs"]]
        return self._metas

    def read_blob(self, meta: BlobMeta) -> bytes:
        if self.ranged and self._data is None:
            out = self.store.read_range(self.key, meta.offset, meta.length)
            self.bytes_read += len(out)
            return out
        data = self._payload()
        return data[meta.offset : meta.offset + meta.length]

    def find(self, blob_type: str, **props) -> BlobMeta | None:
        for m in self.blobs():
            if m.blob_type == blob_type and all(
                m.properties.get(k) == v for k, v in props.items()
            ):
                return m
        return None
