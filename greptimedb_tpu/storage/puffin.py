"""Puffin blob container for per-SST index data.

Role-equivalent of the reference's `puffin` crate (reference
puffin/src/puffin_manager.rs, file_format/): the Apache-Iceberg-Puffin
file layout — magic, concatenated blobs, JSON footer describing blob
offsets/types/properties, footer length, flags, trailing magic — used as
the single sidecar file holding all of an SST's secondary indexes.

Layout (matches the Puffin spec structure):

    "PFA1" | blob_0 | blob_1 | ... | footer_json | footer_len(u32 LE) |
    flags(u32 LE) | "PFA1"
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass, field

MAGIC = b"PFA1"


@dataclass
class BlobMeta:
    blob_type: str  # e.g. "greptime-bloom-filter-v1", "greptime-inverted-index-v1"
    offset: int
    length: int
    properties: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "type": self.blob_type,
            "offset": self.offset,
            "length": self.length,
            "properties": self.properties,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BlobMeta":
        return cls(d["type"], d["offset"], d["length"], d.get("properties", {}))


class PuffinWriter:
    def __init__(self, path: str):
        self.path = path
        self._blobs: list[tuple[BlobMeta, bytes]] = []

    def add_blob(self, blob_type: str, data: bytes, properties: dict | None = None):
        self._blobs.append((BlobMeta(blob_type, 0, len(data), properties or {}), data))

    def finish(self) -> int:
        """Write the container; returns file size. No file if no blobs."""
        if not self._blobs:
            return 0
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            off = len(MAGIC)
            metas = []
            for meta, data in self._blobs:
                meta.offset = off
                f.write(data)
                off += len(data)
                metas.append(meta.to_dict())
            footer = json.dumps({"blobs": metas}).encode()
            f.write(footer)
            f.write(struct.pack("<I", len(footer)))
            f.write(struct.pack("<I", 0))  # flags
            f.write(MAGIC)
        os.replace(tmp, self.path)
        return os.path.getsize(self.path)


class PuffinReader:
    def __init__(self, path: str):
        self.path = path
        self._metas: list[BlobMeta] | None = None

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def blobs(self) -> list[BlobMeta]:
        if self._metas is None:
            with open(self.path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(size - 12)
                tail = f.read(12)
                footer_len = struct.unpack("<I", tail[:4])[0]
                if tail[8:] != MAGIC:
                    raise ValueError(f"bad puffin trailer in {self.path}")
                f.seek(size - 12 - footer_len)
                footer = json.loads(f.read(footer_len))
                f.seek(0)
                if f.read(4) != MAGIC:
                    raise ValueError(f"bad puffin magic in {self.path}")
            self._metas = [BlobMeta.from_dict(d) for d in footer["blobs"]]
        return self._metas

    def read_blob(self, meta: BlobMeta) -> bytes:
        with open(self.path, "rb") as f:
            f.seek(meta.offset)
            return f.read(meta.length)

    def find(self, blob_type: str, **props) -> BlobMeta | None:
        for m in self.blobs():
            if m.blob_type == blob_type and all(
                m.properties.get(k) == v for k, v in props.items()
            ):
                return m
        return None
