"""Streaming k-way merge + dedup over per-source sorted streams.

Role-equivalent of the reference's read pipeline
(mito2/src/read/merge.rs `MergeReader` — a heap of sorted batch sources —
and read/dedup.rs `DedupReader` with its two strategies `LastRow` and
`LastNonNull`): each source yields (pk..., ts, seq)-sorted record batches;
the merger emits globally sorted, deduplicated batches of bounded size, so
peak memory is O(batch) instead of O(scan) — the previous materialized
concat-sort-dedup pass held every source in memory at once.

Mechanics: instead of a per-row heap (Python-loop slow), the merger picks
the source with the smallest head key and emits its rows up to the next
source's head key in one slice (run-cutting — the common case of
non-interleaved sources moves whole batches).  The final key-group of
every emitted chunk is held back until the next round so a (pk, ts) group
can never straddle a chunk boundary; dedup is then chunk-local:

  * last_row:       keep the newest (max seq) version of each (pk, ts)
  * last_non_null:  fieldwise — the newest NON-NULL value per field wins
                    (reference dedup.rs LastNonNull / table option
                    `merge_mode = "last_non_null"`)
"""

from __future__ import annotations

import bisect
from collections.abc import Iterator

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..datatypes.schema import Schema

_SEQ = "__seq"


class _Source:
    """One sorted stream with positioned batch access."""

    def __init__(self, batches: Iterator[pa.Table], key_cols: list[str]):
        self._it = iter(batches)
        self._key_cols = key_cols
        self.batch: pa.Table | None = None
        self.pos = 0
        self._advance_batch()

    def _advance_batch(self):
        self.batch = None
        self.pos = 0
        for b in self._it:
            if b.num_rows:
                self.batch = b
                return

    @property
    def exhausted(self) -> bool:
        return self.batch is None

    def key_at(self, i: int) -> tuple:
        # null-safe ordering: None sorts LAST (matches Arrow's at_end in
        # the memtable sort); (1, 0) > (0, any-value), and values are only
        # compared when both present
        out = []
        for c in self._key_cols:
            v = self.batch[c][i].as_py()
            out.append((1, 0) if v is None else (0, v))
        return tuple(out)

    def head_key(self) -> tuple:
        return self.key_at(self.pos)

    def cut(self, limit: tuple | None) -> pa.Table:
        """Take rows from pos while key <= limit (all remaining rows when
        limit is None), advancing the position/batch."""
        b = self.batch
        if limit is None:
            end = b.num_rows
        else:
            # bisect_right over the batch's sorted keys
            lo, hi = self.pos, b.num_rows
            end = bisect.bisect_right(range(hi), limit, lo=lo, key=self.key_at)
        out = b.slice(self.pos, end - self.pos)
        self.pos = end
        if self.pos >= b.num_rows:
            self._advance_batch()
        return out


def merge_sorted(
    sources: list[Iterator[pa.Table]],
    schema: Schema,
    dedup: bool = True,
    mode: str = "last_row",
    batch_rows: int = 65536,
) -> Iterator[pa.Table]:
    """Merge per-source sorted streams into globally sorted, deduplicated
    batches.  Sources must each be sorted by (pk..., ts) and carry a
    `__seq` int64 column (write order; later sources/rows win).  The
    output drops `__seq`."""
    key_cols = [c.name for c in schema.tag_columns()]
    if schema.time_index is not None:
        key_cols.append(schema.time_index.name)
    srcs = [_Source(b, key_cols) for b in sources]
    srcs = [s for s in srcs if not s.exhausted]

    carry: pa.Table | None = None  # held-back final key-group
    pending: list[pa.Table] = []
    pending_rows = 0

    def flush(chunks: list[pa.Table]) -> pa.Table | None:
        nonlocal carry
        if not chunks:
            return None
        t = pa.concat_tables(chunks, promote_options="permissive")
        if carry is not None:
            t = pa.concat_tables([carry, t], promote_options="permissive")
            carry = None
        if t.num_rows == 0:
            return None
        # hold back the last key-group so it can absorb rows from the next
        # round (a (pk, ts) group must be deduped in one piece)
        def key_of(i: int) -> tuple:
            return tuple(
                (1, 0) if (v := t[c][i].as_py()) is None else (0, v)
                for c in key_cols
            )

        last_key = key_of(t.num_rows - 1)
        first_of_last = t.num_rows - 1
        while first_of_last > 0 and key_of(first_of_last - 1) == last_key:
            first_of_last -= 1
        carry = t.slice(first_of_last)
        t = t.slice(0, first_of_last)
        if t.num_rows == 0:
            return None
        return _dedup_chunk(t, key_cols, schema, dedup, mode)

    while srcs:
        # source with the smallest head key wins; emit its run up to the
        # smallest OTHER head (inclusive — ties meet in the same chunk and
        # are deduped together after the stable seq sort)
        srcs.sort(key=lambda s: s.head_key())
        winner = srcs[0]
        limit = srcs[1].head_key() if len(srcs) > 1 else None
        run = winner.cut(limit)
        if winner.exhausted:
            srcs.remove(winner)
        if run.num_rows:
            pending.append(run)
            pending_rows += run.num_rows
        if pending_rows >= batch_rows:
            out = flush(pending)
            pending, pending_rows = [], 0
            if out is not None and out.num_rows:
                yield out
    out = flush(pending)
    if out is not None and out.num_rows:
        yield out
    if carry is not None and carry.num_rows:
        final = _dedup_chunk(carry, key_cols, schema, dedup, mode)
        if final.num_rows:
            yield final


def _dedup_chunk(
    t: pa.Table, key_cols: list[str], schema: Schema, dedup: bool, mode: str
) -> pa.Table:
    """Chunk-local dedup.  Rows are key-sorted; versions of one key may be
    in any seq order within their group (runs from different sources), so
    sort by (key, seq) first."""
    sort_keys = [(c, "ascending") for c in key_cols] + [(_SEQ, "ascending")]
    idx = pc.sort_indices(t, sort_keys=sort_keys)
    t = t.take(idx)
    if not dedup or t.num_rows <= 1:
        return t.drop_columns([_SEQ]) if _SEQ in t.column_names else t
    keys = [t[c] for c in key_cols]
    n = t.num_rows
    same = np.ones(n - 1, dtype=bool)
    for col in keys:
        a = col.slice(0, n - 1)
        b = col.slice(1)
        eq = pc.equal(a, b)
        both_null = pc.and_(pc.is_null(a), pc.is_null(b))
        same &= np.asarray(pc.fill_null(pc.or_(eq, both_null), False))
    group_last = np.concatenate([~same, [True]])
    if mode == "last_non_null":
        from .region import OP_COL

        if OP_COL in t.column_names:
            # a delete tombstone kills every version at or before it
            # (reference dedup.rs LastNonNull skips deleted versions);
            # the group's newest delete index is broadcast to ALL of the
            # group's rows so earlier versions die too
            n2 = t.num_rows
            op = np.asarray(
                pc.fill_null(pc.cast(t[OP_COL], pa.int64()), 0)
            )
            ridx = np.arange(n2, dtype=np.int64)
            dcand = np.where(op != 0, ridx, -1)
            starts = np.nonzero(np.concatenate([[True], ~same]))[0]
            gmax_del = np.maximum.reduceat(dcand, starts)
            bcast = np.repeat(gmax_del, np.diff(np.append(starts, n2)))
            keep = ridx > bcast
            t = t.filter(pa.array(keep)).drop_columns([OP_COL])
            if t.num_rows == 0:
                return t.drop_columns([_SEQ]) if _SEQ in t.column_names else t
            # groups changed: recompute boundaries
            keys2 = [t[c] for c in key_cols]
            m = t.num_rows
            if m > 1:
                same2 = np.ones(m - 1, dtype=bool)
                for col in keys2:
                    a2, b2 = col.slice(0, m - 1), col.slice(1)
                    eq2 = pc.equal(a2, b2)
                    bn2 = pc.and_(pc.is_null(a2), pc.is_null(b2))
                    same2 &= np.asarray(pc.fill_null(pc.or_(eq2, bn2), False))
                group_last = np.concatenate([~same2, [True]])
            else:
                group_last = np.ones(m, dtype=bool)
        t = _last_non_null(t, group_last, schema, key_cols)
    else:
        t = t.filter(pa.array(group_last))
    return t.drop_columns([_SEQ]) if _SEQ in t.column_names else t


def _last_non_null(
    t: pa.Table, group_last: np.ndarray, schema: Schema, key_cols: list[str]
) -> pa.Table:
    """Fieldwise merge: for each (pk, ts) group take the newest NON-NULL
    value of every field column (reference read/dedup.rs LastNonNull).
    Vectorized: forward-fill each field within groups (seq-ascending rows)
    via a masked running index, then gather at group-last rows."""
    n = t.num_rows
    group_id = np.concatenate([[0], np.cumsum(group_last[:-1])]).astype(np.int64)
    last_rows = np.nonzero(group_last)[0]
    arrays: dict[str, pa.Array] = {}
    key_set = set(key_cols)
    for name in t.column_names:
        if name == _SEQ:
            continue
        col = t[name].combine_chunks()
        if name in key_set:
            arrays[name] = col.take(pa.array(last_rows))
            continue
        valid = np.asarray(pc.is_valid(col))
        ridx = np.arange(n, dtype=np.int64)
        # running "latest non-null row index" via max-accumulate; a carry
        # from a previous group is detected by group-id mismatch and
        # treated as no-value
        cand = np.where(valid, ridx, -1)
        ff = np.maximum.accumulate(cand)
        has = ff >= 0
        ok = has & (group_id[np.clip(ff, 0, None)] == group_id)
        pick = ff[last_rows]
        pick_ok = ok[last_rows]
        taken = col.take(pa.array(np.where(pick_ok, pick, 0)))
        if not pick_ok.all():
            mask = pa.array(~pick_ok)
            taken = pc.if_else(mask, pa.nulls(len(last_rows), taken.type), taken)
        arrays[name] = taken
    return pa.table({name: arrays[name] for name in t.column_names if name != _SEQ})
