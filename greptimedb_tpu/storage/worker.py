"""Sharded region-worker write loops with request batching.

Role-equivalent of the reference's `WorkerGroup`/`RegionWorkerLoop`
(mito2/src/worker.rs:136,459,863): requests are hashed to one of
`num_workers` single-threaded loops by region id (`region_id_to_index` —
one writer per region, races structured out), and each loop drains its
queue in batches of up to `worker_request_batch_size`, grouping writes by
region so one WAL append + memtable insert covers many requests
(worker/handle_write.rs stages the same batching).

The synchronous `TimeSeriesEngine.write` remains the single-region
path; the Database inserter pipelines MULTI-REGION writes through the
group (database.py write_batch) so per-region WAL appends overlap.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from dataclasses import dataclass

import pyarrow as pa


@dataclass
class _WriteRequest:
    region_id: int
    batch: pa.RecordBatch
    future: Future


class RegionWorkerLoop:
    """One single-threaded worker: the only writer for its region subset
    (reference RegionWorkerLoop, worker.rs:863 — `tokio::select!` over the
    request channel; here a queue.get with a drain)."""

    def __init__(self, engine, index: int, batch_size: int):
        self.engine = engine
        self.index = index
        self.batch_size = batch_size
        self.stopped = False
        self.queue: queue.Queue[_WriteRequest | None] = queue.Queue()
        self.thread = threading.Thread(
            target=self._run, name=f"region-worker-{index}", daemon=True
        )
        self.thread.start()

    def submit(self, req: _WriteRequest):
        if self.stopped:
            req.future.set_exception(
                RuntimeError("region worker group is stopped")
            )
            return
        self.queue.put(req)

    def stop(self):
        self.stopped = True
        self.queue.put(None)
        self.thread.join(timeout=10)
        # fail anything still queued: a caller blocked on future.result()
        # must see shutdown, not hang
        while True:
            try:
                req = self.queue.get_nowait()
            except queue.Empty:
                break
            if req is not None and not req.future.done():
                req.future.set_exception(
                    RuntimeError("region worker group stopped before write ran")
                )

    def _run(self):
        while True:
            req = self.queue.get()
            if req is None:
                return
            batch = [req]
            # drain: batch up to batch_size requests per wakeup
            while len(batch) < self.batch_size:
                try:
                    nxt = self.queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._handle(batch)
                    return
                batch.append(nxt)
            self._handle(batch)

    def _handle(self, reqs: list[_WriteRequest]):
        """Group by region; one WAL frame + one memtable lock per (region,
        drained group) (reference handle_write_requests,
        worker/handle_write.rs:40).  With ingest.group_commit on the group
        commits through engine.write_group — ONE frame carrying one entry
        id per request, so replay/lag/prune semantics match frame-per-
        write.  Off restores the legacy merge (one batch, one entry id)
        bit-for-bit."""
        by_region: dict[int, list[_WriteRequest]] = {}
        for r in reqs:
            by_region.setdefault(r.region_id, []).append(r)
        for rid, group in by_region.items():
            try:
                if len(group) == 1:
                    rows = self.engine.write(rid, group[0].batch)
                    self._stamp_stages(rid, group)
                    group[0].future.set_result(rows)
                    continue
                write_group = getattr(self.engine, "write_group", None)
                if write_group is not None and getattr(
                    getattr(self.engine, "config", None),
                    "ingest_group_commit", True,
                ):
                    rows_list = write_group(rid, [g.batch for g in group])
                    self._stamp_stages(rid, group)
                    for g, n in zip(group, rows_list):
                        g.future.set_result(n)
                    continue
                merged = pa.Table.from_batches(
                    [g.batch for g in group]
                ).combine_chunks()
                self.engine.write(
                    rid, merged.to_batches()[0]
                    if merged.num_rows
                    else group[0].batch
                )
                self._stamp_stages(rid, group)
                for g in group:
                    g.future.set_result(g.batch.num_rows)
            except Exception as e:  # noqa: BLE001 — deliver per-request
                for g in group:
                    if not g.future.done():
                        g.future.set_exception(e)

    def _stamp_stages(self, rid: int, group: list[_WriteRequest]):
        """Attach the write's per-stage wall to each request's future
        BEFORE resolving it: the submitting thread reads it off the
        future, so a concurrent caller's later write on this region can
        never be mis-attributed to this statement's write.region span."""
        try:
            stages = self.engine.region(rid).last_write_stage_ms
        except Exception:  # noqa: BLE001 — attribution only
            return
        for g in group:
            g.future.stage_ms = stages


class WorkerGroup:
    """Hash regions across workers (reference WorkerGroup, worker.rs:136;
    region_id_to_index :459)."""

    def __init__(self, engine, num_workers: int = 4, batch_size: int = 64):
        self.workers = [
            RegionWorkerLoop(engine, i, batch_size) for i in range(max(num_workers, 1))
        ]

    def _worker_for(self, region_id: int) -> RegionWorkerLoop:
        return self.workers[region_id % len(self.workers)]

    def submit_write(self, region_id: int, batch: pa.RecordBatch) -> Future:
        fut: Future = Future()
        self._worker_for(region_id).submit(_WriteRequest(region_id, batch, fut))
        return fut

    def write(self, region_id: int, batch: pa.RecordBatch, timeout: float = 60.0) -> int:
        return self.submit_write(region_id, batch).result(timeout)

    def stop(self):
        for w in self.workers:
            w.stop()
