"""Background maintenance: the compaction scheduler.

Role-equivalent of the reference's `CompactionScheduler` driven off the
region worker loop (reference mito2/src/compaction.rs + worker.rs periodic
tick + flush-finished notifications): flushes nudge the scheduler, a
periodic tick catches anything missed, and each round runs the TWCS picker
(`compaction.py`) over the flagged regions.  Without this, L0 accumulates
until an explicit `ADMIN compact_table` — scans degrade silently.

One daemon thread per engine; per-region work is serialized by the region's
own lock (compaction commits via `apply_compaction`), and a region is never
compacted concurrently with itself because the scheduler is the only
automatic driver.
"""

from __future__ import annotations

import threading

from ..utils import metrics


class FollowerSyncer:
    """Follower freshness loop (replica.sync_interval_ms): every interval,
    each READ-ONLY region this engine hosts replays the shared-WAL tail
    past its applied entry id and refreshes its manifest view when the
    leader's version advanced — so hedged reads against followers are
    bounded-staleness instead of frozen-at-open snapshots.

    One daemon thread per engine (like FlushScheduler); a round's failures
    are per-region and retried next round (Region.follower_sync resumes
    from the persisted applied position).  `sync_now()` runs one round
    inline for deterministic tests."""

    def __init__(self, engine, interval_ms: float):
        self.engine = engine
        self.interval_s = max(interval_ms, 1.0) / 1000.0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="follower-sync", daemon=True
        )
        self._thread.start()

    def sync_now(self) -> dict[int, int]:
        return self.engine.sync_followers()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.engine.sync_followers()
            except Exception:  # noqa: BLE001 — engine logs per-region; a
                # whole-round failure must never kill the loop
                pass


class FlushScheduler:
    """Background flush worker: threshold-triggered flushes run OFF the
    write path (reference mito2/src/flush.rs FlushScheduler — the write
    loop only signals; a scheduler task does the Parquet encode + upload).
    Stall-triggered flushes stay synchronous in the engine: that is the
    backpressure mechanism, not an optimization target.

    This is the §2.5 pipeline-parallelism axis for ingest: WAL append +
    memtable insert proceed for new writes while earlier memtables encode
    to SSTs on this thread."""

    def __init__(self, engine):
        self.engine = engine
        self._cv = threading.Condition()
        self._pending: set[int] = set()
        self._inflight: set[int] = set()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, name="flush-scheduler", daemon=True)
        self._thread.start()

    def schedule(self, region_id: int):
        with self._cv:
            # always enqueue — a trigger during an in-flight flush means NEW
            # rows landed in the fresh memtable; dropping it would leave an
            # over-threshold memtable unflushed once writes stop
            self._pending.add(region_id)
            self._cv.notify()

    def wait_idle(self, timeout: float = 30.0):
        """Block until no flush is pending or running (tests, shutdown)."""
        import time as _t

        deadline = _t.monotonic() + timeout
        with self._cv:
            while (self._pending or self._inflight) and _t.monotonic() < deadline:
                self._cv.wait(0.05)

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=10)

    def _loop(self):
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait(1.0)
                if self._stop and not self._pending:
                    return
                rid = self._pending.pop()
                self._inflight.add(rid)
            try:
                self.engine.flush_region(rid)
            except Exception:  # noqa: BLE001 — a failed flush retries on the
                # next threshold trip; the WAL still holds the data
                pass
            finally:
                with self._cv:
                    self._inflight.discard(rid)
                    self._cv.notify_all()


class CompactionScheduler:
    def __init__(
        self,
        engine,
        tick_secs: float = 5.0,
        window_ms: int | None = None,
        max_active_runs: int = 4,
        max_inactive_runs: int = 1,
        memory_mb: int = 512,
    ):
        self.engine = engine
        self.tick_secs = tick_secs
        self.window_ms = window_ms
        self.max_active_runs = max_active_runs
        self.max_inactive_runs = max_inactive_runs
        self.memory_mb = memory_mb
        self._cv = threading.Condition()
        self._dirty: set[int] = set()
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="compaction-scheduler", daemon=True
        )
        self._rounds = 0
        self._thread.start()

    # ---- signals -----------------------------------------------------------
    def notify_flush(self, region_id: int):
        """A flush added an L0 file — check this region soon."""
        with self._cv:
            self._dirty.add(region_id)
            self._cv.notify()

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=10)

    def run_once(self) -> int:
        """One synchronous round over every region (tests + ADMIN path)."""
        from .compaction import compact_region

        done = 0
        for rid in self.engine.region_ids():
            try:
                region = self.engine.region(rid)
            except Exception:  # noqa: BLE001 — region closed mid-round
                continue
            if not getattr(region, "writable", True):
                # follower replica / downgraded leader: compaction belongs
                # to the leader — two compactors on shared storage would
                # corrupt the manifest
                continue
            try:
                done += compact_region(
                    region,
                    window_ms=self.window_ms,
                    max_active_runs=self.max_active_runs,
                    max_inactive_runs=self.max_inactive_runs,
                    memory_mb=self.memory_mb,
                )
            except Exception:  # noqa: BLE001 — keep the scheduler alive
                metrics.COMPACTION_FAILED.inc()
        self._rounds += 1
        return done

    # ---- loop --------------------------------------------------------------
    def _loop(self):
        from .compaction import compact_region

        while True:
            with self._cv:
                self._cv.wait(timeout=self.tick_secs)
                if self._stop:
                    return
                dirty = self._dirty
                self._dirty = set()
            region_ids = list(dirty) if dirty else self.engine.region_ids()
            for rid in region_ids:
                with self._cv:
                    if self._stop:
                        return
                try:
                    region = self.engine.region(rid)
                except Exception:  # noqa: BLE001 — closed between list and get
                    continue
                if not getattr(region, "writable", True):
                    continue  # follower replica: the leader compacts
                try:
                    n = compact_region(
                        region,
                        window_ms=self.window_ms,
                        max_active_runs=self.max_active_runs,
                        max_inactive_runs=self.max_inactive_runs,
                        memory_mb=self.memory_mb,
                    )
                    if n:
                        metrics.COMPACTION_BACKGROUND.inc(n)
                except Exception:  # noqa: BLE001 — never kill the loop
                    metrics.COMPACTION_FAILED.inc()
            self._rounds += 1
