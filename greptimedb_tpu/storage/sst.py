"""Parquet SST read/write with time-range pruning.

Role-equivalent of the reference's SST layer (reference
src/mito2/src/sst/parquet/{writer.rs,reader.rs,stats.rs}): immutable sorted
Parquet files with min/max time statistics used to prune whole files and row
groups at scan time.  We persist data in the reference's "flat format"
(flat_format.rs) spirit — plain columnar, tags as dictionary-encoded
columns — because flat columns are exactly what the TPU tile loader wants.
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from ..datatypes.schema import Schema
from ..utils import fault_injection, metrics
from ..utils.deadline import check_deadline, current_deadline
from . import index as idx
from .index import BLOOM_BLOB, FULLTEXT_BLOB, INVERTED_BLOB, VECTOR_BLOB
from .object_store import FsObjectStore, ObjectStore
from .puffin import PuffinReader, PuffinWriter

DEFAULT_ROW_GROUP_SIZE = 1 << 20  # rows per row group; big groups = big tiles

INDEX_FULLTEXT_PRUNES = metrics.Counter(
    "greptime_index_fulltext_applied_total",
    "match predicates answered by the fulltext index",
)
INDEX_PRUNED_GROUPS = metrics.Counter(
    "sst_index_pruned_row_groups", "row groups skipped via secondary indexes"
)
INDEX_VECTOR_APPLIED = metrics.Counter(
    "greptime_index_vector_applied_total",
    "top-k vector searches answered via the IVF index",
)


@dataclass
class FileMeta:
    """Catalog entry for one SST (reference mito2/src/sst/file.rs FileMeta)."""

    file_id: str
    time_range: tuple[int, int]  # [min_ts, max_ts] inclusive, int64 native unit
    num_rows: int
    file_size: int
    level: int = 0
    indexed_columns: list[str] = field(default_factory=list)
    index_file_size: int = 0
    # Delete-tombstone rows in the file; -1 = unknown (file written before
    # this field existed).  The device tile cache only aggregates files it
    # can PROVE tombstone-free.
    num_deletes: int = 0

    def to_dict(self) -> dict:
        return {
            "file_id": self.file_id,
            "time_range": list(self.time_range),
            "num_rows": self.num_rows,
            "file_size": self.file_size,
            "level": self.level,
            "indexed_columns": self.indexed_columns,
            "index_file_size": self.index_file_size,
            "num_deletes": self.num_deletes,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FileMeta":
        return cls(
            file_id=d["file_id"],
            time_range=tuple(d["time_range"]),
            num_rows=d["num_rows"],
            file_size=d["file_size"],
            level=d.get("level", 0),
            indexed_columns=d.get("indexed_columns", []),
            index_file_size=d.get("index_file_size", 0),
            num_deletes=d.get("num_deletes", -1),
        )


def interleaved_overlap_unsafe(
    inputs: list[FileMeta],
    all_files: list[FileMeta],
    pos: dict[str, int],
) -> bool:
    """True when merging `inputs` cannot express last-write-wins with ONE
    output manifest position: some file outside the group both
    time-overlaps an input (so they may share (pk, ts) keys) and sits
    BETWEEN the group's manifest positions (so it is newer than some
    inputs and older than others).  Shared by the compaction picker and
    the commit gate in Region.apply_compaction — the two must never
    diverge (scans rank duplicate versions by manifest position; the
    reference persists per-row sequences instead, mito2/src/read/dedup.rs)."""
    in_ids = {f.file_id for f in inputs}
    ps = sorted(pos[f.file_id] for f in inputs)
    if len(ps) <= 1:
        return False
    lo, hi = ps[0], ps[-1]
    for x in all_files:
        if x.file_id in in_ids or not (lo < pos.get(x.file_id, -1) < hi):
            continue
        for g in inputs:
            if (
                x.time_range[1] >= g.time_range[0]
                and x.time_range[0] <= g.time_range[1]
            ):
                return True
    return False


@dataclass
class ScanPredicate:
    """Pushed-down predicates the reader can use for pruning: a time range
    plus simple column comparisons (reference sst/parquet/stats.rs)."""

    time_range: tuple[int, int] | None = None  # [lo, hi) half-open
    # list of (column, op, value) with op in {"=", "!=", "<", "<=", ">", ">=", "in"}
    filters: list[tuple[str, str, object]] = field(default_factory=list)


class SstWriter:
    def __init__(
        self,
        store: ObjectStore | str,
        schema: Schema,
        row_group_size: int = DEFAULT_ROW_GROUP_SIZE,
        index_enable: bool = True,
        index_segment_rows: int = idx.DEFAULT_SEGMENT_ROWS,
        index_inverted_max_terms: int = 4096,
        index_segmented: bool = True,
        index_segment_terms: int = 512,
        index_max_terms: int = 1 << 20,
    ):
        # A bare directory path means "local fs store rooted there" — the
        # common standalone config and what unit tests pass.
        self.store = FsObjectStore(store) if isinstance(store, str) else store
        self.schema = schema
        self.row_group_size = row_group_size
        self.index_enable = index_enable
        self.index_segment_rows = index_segment_rows
        self.index_inverted_max_terms = index_inverted_max_terms
        # Segmented term index (greptimedb_tpu/index/): fence-keyed term
        # segments with ranged reads.  On (the default) it REPLACES the
        # whole-blob inverted/fulltext payloads for new SSTs and lifts
        # the legacy cardinality cap to `index_max_terms`; off restores
        # the legacy formats bit-for-bit (old sidecars stay readable
        # either way — the read router handles both).
        self.index_segmented = index_segmented
        self.index_segment_terms = index_segment_terms
        self.index_max_terms = index_max_terms

    def _build_indexes(self, table: pa.Table, file_id: str) -> tuple[list[str], int]:
        """Build bloom + term indexes over tag columns, and tokenized
        fulltext indexes over FULLTEXT-declared text columns, into the
        puffin sidecar (reference mito2/src/sst/index/indexer/ builds
        during flush; fulltext_index/ for the tantivy analogue)."""
        from .. import index as term_index

        cols = [c.name for c in self.schema.tag_columns() if c.name in table.column_names]
        ft_cols = [
            c.name
            for c in self.schema.columns
            if getattr(c, "fulltext", False) and c.name in table.column_names
        ]
        vec_cols = [
            c
            for c in self.schema.columns
            if getattr(c, "vector_index", False) and c.name in table.column_names
        ]
        if not cols and not ft_cols and not vec_cols:
            return [], 0
        fault_injection.fire("index.build", file=file_id)
        writer = PuffinWriter(self.store, f"{file_id}.puffin")
        indexed = []
        for name in cols:
            col = table[name]
            col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
            bloom = idx.build_bloom_index(col, self.index_segment_rows)
            writer.add_blob(BLOOM_BLOB, bloom, {"column": name})
            if self.index_segmented:
                terms, postings, n_segs = term_index.build_term_postings(
                    col, self.index_segment_rows
                )
                if len(terms) <= self.index_max_terms:
                    term_index.write_term_index(
                        writer, name, "inverted", terms, postings,
                        segment_rows=self.index_segment_rows,
                        n_rows=len(col), n_segs=n_segs,
                        seg_terms=self.index_segment_terms,
                    )
            else:
                inverted = idx.build_inverted_index(
                    col, self.index_segment_rows, self.index_inverted_max_terms
                )
                if inverted is not None:
                    writer.add_blob(INVERTED_BLOB, inverted, {"column": name})
            indexed.append(name)
        for name in ft_cols:
            col = table[name]
            col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
            if self.index_segmented:
                toks, postings, n_segs = term_index.build_token_postings(
                    col, self.index_segment_rows
                )
                if toks and len(toks) <= self.index_max_terms:
                    term_index.write_term_index(
                        writer, name, "fulltext", toks, postings,
                        segment_rows=self.index_segment_rows,
                        n_rows=len(col), n_segs=n_segs,
                        seg_terms=self.index_segment_terms,
                    )
                    if name not in indexed:
                        indexed.append(name)
            else:
                ft = idx.build_fulltext_index(col, self.index_segment_rows)
                if ft is not None:
                    writer.add_blob(FULLTEXT_BLOB, ft, {"column": name})
                    if name not in indexed:
                        indexed.append(name)
        for c in vec_cols:
            col = table[c.name]
            col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
            vec = idx.build_vector_index(col, c.vector_dim or 0)
            if vec is not None:
                writer.add_blob(VECTOR_BLOB, vec, {"column": c.name})
                if c.name not in indexed:
                    indexed.append(c.name)
        return indexed, writer.finish()

    def write(self, table: pa.Table, level: int = 0) -> FileMeta | None:
        """Write one sorted table as one SST file; returns its FileMeta."""
        if table.num_rows == 0:
            return None
        ts_name = self.schema.time_index.name if self.schema.time_index else None
        if ts_name is not None:
            ts = pc.cast(table[ts_name], pa.int64())
            t_min, t_max = pc.min(ts).as_py(), pc.max(ts).as_py()
        else:
            t_min = t_max = 0
        num_deletes = 0
        if "__op" in table.column_names:
            num_deletes = int(
                pc.sum(
                    pc.fill_null(pc.cast(table["__op"], pa.int64()), 0)
                ).as_py()
                or 0
            )
        # Dictionary-encode tag columns: small files + pre-built codes for TPU.
        for tag in self.schema.tag_columns():
            if tag.name in table.column_names and not pa.types.is_dictionary(
                table.schema.field(tag.name).type
            ):
                i = table.schema.get_field_index(tag.name)
                table = table.set_column(
                    i, tag.name, pc.dictionary_encode(table[tag.name].combine_chunks())
                )
        file_id = uuid.uuid4().hex
        key = f"{file_id}.parquet"
        scratch = self.store.scratch_path(key)
        pq.write_table(
            table,
            scratch,
            row_group_size=self.row_group_size,
            compression="zstd",
            use_dictionary=True,
        )
        file_size = os.path.getsize(scratch)
        self.store.put_file(key, scratch)
        indexed, index_size = ([], 0)
        if self.index_enable:
            try:
                indexed, index_size = self._build_indexes(table, file_id)
            except Exception as e:  # noqa: BLE001 — an index build failure
                # must never lose the data write: the SST lands without a
                # sidecar (unpruned but correct), and the failure is loud
                import logging

                logging.getLogger("greptimedb_tpu.index").warning(
                    "index build for %s failed; SST written unindexed: %s",
                    file_id, e,
                )
                indexed, index_size = [], 0
        return FileMeta(
            file_id=file_id,
            time_range=(t_min, t_max),
            num_rows=table.num_rows,
            file_size=file_size,
            level=level,
            indexed_columns=indexed,
            index_file_size=index_size,
            num_deletes=num_deletes,
        )


_INDEX_CACHE = idx.IndexCache(capacity=128)


class SstReader:
    def __init__(self, store: ObjectStore | str, schema: Schema):
        self.store = FsObjectStore(store) if isinstance(store, str) else store
        self.schema = schema

    def delete(self, file_id: str):
        """Remove an SST and its index sidecar from the store."""
        self.store.delete(f"{file_id}.parquet")
        self.store.delete(f"{file_id}.puffin")

    def prune_files(self, files: list[FileMeta], pred: ScanPredicate) -> list[FileMeta]:
        """File-level pruning on time range (whole-file min/max)."""
        if pred.time_range is None:
            return list(files)
        lo, hi = pred.time_range
        return [f for f in files if f.time_range[1] >= lo and f.time_range[0] < hi]

    def read(
        self,
        meta: FileMeta,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
    ) -> pa.Table:
        """Read one SST with row-group pruning + residual filter application."""
        pred = pred or ScanPredicate()
        pf = pq.ParquetFile(self.store.open_input(f"{meta.file_id}.parquet"))
        ts_name = self.schema.time_index.name if self.schema.time_index else None
        groups = self._prune_row_groups(pf, pred, ts_name)
        if groups and meta.indexed_columns:
            before = len(groups)
            groups = self._prune_with_indexes(pf, meta, pred, groups)
            if len(groups) < before:
                INDEX_PRUNED_GROUPS.inc(before - len(groups))
        if columns:
            # tolerate requested columns the file predates (e.g. __op or a
            # column added by ALTER after this SST was written)
            columns = [c for c in columns if c in pf.schema_arrow.names]
        if not groups:
            schema = pf.schema_arrow
            if columns:
                schema = pa.schema([schema.field(c) for c in columns])
            return schema.empty_table()
        check_deadline()
        if current_deadline() is None or len(groups) <= 4:
            table = pf.read_row_groups(groups, columns=columns, use_threads=True)
        else:
            # under an active deadline, decode in row-group batches so a
            # runaway scan aborts between batches instead of grinding
            # through the whole file in one opaque C call
            parts = []
            for i in range(0, len(groups), 4):
                check_deadline()
                parts.append(
                    pf.read_row_groups(groups[i : i + 4], columns=columns, use_threads=True)
                )
            table = pa.concat_tables(parts)
        # Parquet has no seconds timestamp unit: a timestamp("s") column comes
        # back as timestamp("ms").  Restore the declared logical type so
        # residual predicates (expressed in the native unit) compare correctly.
        if ts_name is not None and ts_name in table.column_names:
            want = self.schema.time_index.data_type.to_arrow()
            i = table.schema.get_field_index(ts_name)
            if table.schema.field(i).type != want:
                table = table.set_column(i, ts_name, pc.cast(table[ts_name], want))
        table = _apply_residual(table, pred, ts_name)
        return table

    def read_batches(
        self,
        meta: FileMeta,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
    ):
        """Stream one SST row-group at a time (reference FileRange scan
        units, mito2/src/sst/parquet/reader.rs): the streaming merge reader
        holds at most one row group per source in memory."""
        pred = pred or ScanPredicate()
        pf = pq.ParquetFile(self.store.open_input(f"{meta.file_id}.parquet"))
        ts_name = self.schema.time_index.name if self.schema.time_index else None
        groups = self._prune_row_groups(pf, pred, ts_name)
        if groups and meta.indexed_columns:
            groups = self._prune_with_indexes(pf, meta, pred, groups)
        if columns:
            columns = [c for c in columns if c in pf.schema_arrow.names]
        want = (
            self.schema.time_index.data_type.to_arrow()
            if self.schema.time_index
            else None
        )
        for g in groups:
            table = pf.read_row_groups([g], columns=columns, use_threads=False)
            if ts_name is not None and ts_name in table.column_names:
                i = table.schema.get_field_index(ts_name)
                if want is not None and table.schema.field(i).type != want:
                    table = table.set_column(i, ts_name, pc.cast(table[ts_name], want))
            table = _apply_residual(table, pred, ts_name)
            if table.num_rows:
                yield table

    def _prune_with_indexes(
        self, pf: pq.ParquetFile, meta: FileMeta, pred: ScanPredicate, groups: list[int]
    ) -> list[int]:
        """Row-group pruning via the puffin sidecar, routed through the
        shared TermIndexReader (reference mito2/src/read/scan_region.rs
        index appliers): segmented term index with ranged reads when the
        sidecar carries it, legacy whole-blob parses otherwise.  Any
        index failure degrades to no pruning — the residual filter keeps
        results exact."""
        usable = [
            (name, op, value)
            for name, op, value in pred.filters
            if name in meta.indexed_columns
            and op in ("=", "in", "!=", "match", "match_term")
        ]
        if not usable:
            return groups
        reader = self.term_index(meta)
        if reader is None:
            return groups
        seg_bitmap: np.ndarray | None = None
        for name, op, value in usable:
            bm = reader.search(name, op, value)
            if bm is None:
                continue
            if op in ("match", "match_term"):
                INDEX_FULLTEXT_PRUNES.inc()
            seg_bitmap = bm if seg_bitmap is None else (seg_bitmap & bm)
        if seg_bitmap is None:
            return groups
        seg_rows = reader.segment_rows()
        md = pf.metadata
        offsets = [0]
        for g in range(md.num_row_groups):
            offsets.append(offsets[-1] + md.row_group(g).num_rows)
        keep = []
        for g in groups:
            s0 = offsets[g] // seg_rows
            s1 = (offsets[g + 1] - 1) // seg_rows
            if seg_bitmap[s0 : s1 + 1].any():
                keep.append(g)
        return keep

    def term_index(self, meta: FileMeta):
        """The file's cached TermIndexReader, or None without a sidecar."""
        from ..index import TermIndexReader

        cached = _INDEX_CACHE.get(meta.file_id)
        if cached is not None:
            return cached
        reader = TermIndexReader(self.store, meta.file_id)
        if not reader.exists():
            return None
        _INDEX_CACHE.put(meta.file_id, reader)
        return reader

    def distinct_terms(self, meta: FileMeta, column: str) -> int | None:
        """Unique-term count of `column` in this SST from the segmented
        index meta (one small ranged read; None when unindexed) — the
        planner's distinct-key stats feed."""
        reader = self.term_index(meta)
        return None if reader is None else reader.distinct_terms(column)

    def vector_index(self, meta: FileMeta, column: str):
        """Parsed per-SST IVF index for `column`, or None."""
        reader = self.term_index(meta)
        return None if reader is None else reader.vector_index(column)

    def _prune_row_groups(self, pf: pq.ParquetFile, pred: ScanPredicate, ts_name) -> list[int]:
        md = pf.metadata
        if pred.time_range is None or ts_name is None:
            return list(range(md.num_row_groups))
        ts_idx = pf.schema_arrow.get_field_index(ts_name)
        if ts_idx < 0:
            return list(range(md.num_row_groups))  # no stats to prune on
        unit_ns = self.schema.time_index.data_type.timestamp_unit_ns()
        lo, hi = pred.time_range
        keep = []
        for g in range(md.num_row_groups):
            stats = md.row_group(g).column(ts_idx).statistics
            if stats is None or not stats.has_min_max:
                keep.append(g)
                continue
            g_min, g_max = _ts_to_int(stats.min, unit_ns), _ts_to_int(stats.max, unit_ns)
            if g_max >= lo and g_min < hi:
                keep.append(g)
        return keep


def _ts_to_int(v, unit_ns: int) -> int:
    """Convert a parquet stats value to the column's NATIVE timestamp unit.

    pyarrow surfaces timestamp stats as datetimes; predicates arrive in the
    column's own unit, so scale by the schema's unit (not hardcoded ms)."""
    if hasattr(v, "timestamp"):
        import calendar

        ns = calendar.timegm(v.utctimetuple()) * 1_000_000_000 + v.microsecond * 1000
        return ns // unit_ns
    return int(v)


def _apply_residual(table: pa.Table, pred: ScanPredicate, ts_name) -> pa.Table:
    """Apply exact time-range + pushed filters on the decoded table."""
    if table.num_rows == 0:
        return table
    mask = None
    if pred.time_range is not None and ts_name is not None and ts_name in table.column_names:
        lo, hi = pred.time_range
        ts = pc.cast(table[ts_name], pa.int64())
        mask = pc.and_(pc.greater_equal(ts, lo), pc.less(ts, hi))
    for name, op, value in pred.filters:
        if name not in table.column_names:
            continue
        col = table[name]
        if pa.types.is_dictionary(col.type):
            col = pc.cast(col, col.type.value_type)
        m = _cmp(col, op, value)
        mask = m if mask is None else pc.and_(mask, m)
    if mask is not None:
        table = table.filter(mask)
    return table


def _cmp(col, op: str, value):
    if op == "match":
        return idx.matches_mask(col, value)
    if op == "match_term":
        return idx.matches_term_mask(col, value)
    if isinstance(value, str):
        from ..datatypes.coercion import coerce_string_scalar

        value = coerce_string_scalar(value, col.type)
    if op == "=":
        return pc.equal(col, value)
    if op == "!=":
        return pc.not_equal(col, value)
    if op == "<":
        return pc.less(col, value)
    if op == "<=":
        return pc.less_equal(col, value)
    if op == ">":
        return pc.greater(col, value)
    if op == ">=":
        return pc.greater_equal(col, value)
    if op == "in":
        return pc.is_in(col, value_set=pa.array(list(value)))
    if op == "not in":
        return pc.invert(pc.is_in(col, value_set=pa.array(list(value))))
    raise ValueError(f"unknown filter op: {op}")
