"""Parquet SST read/write with time-range pruning.

Role-equivalent of the reference's SST layer (reference
src/mito2/src/sst/parquet/{writer.rs,reader.rs,stats.rs}): immutable sorted
Parquet files with min/max time statistics used to prune whole files and row
groups at scan time.  We persist data in the reference's "flat format"
(flat_format.rs) spirit — plain columnar, tags as dictionary-encoded
columns — because flat columns are exactly what the TPU tile loader wants.
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from ..datatypes.schema import Schema

DEFAULT_ROW_GROUP_SIZE = 1 << 20  # rows per row group; big groups = big tiles


@dataclass
class FileMeta:
    """Catalog entry for one SST (reference mito2/src/sst/file.rs FileMeta)."""

    file_id: str
    time_range: tuple[int, int]  # [min_ts, max_ts] inclusive, int64 native unit
    num_rows: int
    file_size: int
    level: int = 0

    def to_dict(self) -> dict:
        return {
            "file_id": self.file_id,
            "time_range": list(self.time_range),
            "num_rows": self.num_rows,
            "file_size": self.file_size,
            "level": self.level,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FileMeta":
        return cls(
            file_id=d["file_id"],
            time_range=tuple(d["time_range"]),
            num_rows=d["num_rows"],
            file_size=d["file_size"],
            level=d.get("level", 0),
        )


@dataclass
class ScanPredicate:
    """Pushed-down predicates the reader can use for pruning: a time range
    plus simple column comparisons (reference sst/parquet/stats.rs)."""

    time_range: tuple[int, int] | None = None  # [lo, hi) half-open
    # list of (column, op, value) with op in {"=", "!=", "<", "<=", ">", ">=", "in"}
    filters: list[tuple[str, str, object]] = field(default_factory=list)


class SstWriter:
    def __init__(self, sst_dir: str, schema: Schema, row_group_size: int = DEFAULT_ROW_GROUP_SIZE):
        self.sst_dir = sst_dir
        self.schema = schema
        self.row_group_size = row_group_size
        os.makedirs(sst_dir, exist_ok=True)

    def write(self, table: pa.Table, level: int = 0) -> FileMeta | None:
        """Write one sorted table as one SST file; returns its FileMeta."""
        if table.num_rows == 0:
            return None
        ts_name = self.schema.time_index.name if self.schema.time_index else None
        if ts_name is not None:
            ts = pc.cast(table[ts_name], pa.int64())
            t_min, t_max = pc.min(ts).as_py(), pc.max(ts).as_py()
        else:
            t_min = t_max = 0
        # Dictionary-encode tag columns: small files + pre-built codes for TPU.
        for tag in self.schema.tag_columns():
            if tag.name in table.column_names and not pa.types.is_dictionary(
                table.schema.field(tag.name).type
            ):
                i = table.schema.get_field_index(tag.name)
                table = table.set_column(
                    i, tag.name, pc.dictionary_encode(table[tag.name].combine_chunks())
                )
        file_id = uuid.uuid4().hex
        path = self._path(file_id)
        pq.write_table(
            table,
            path,
            row_group_size=self.row_group_size,
            compression="zstd",
            use_dictionary=True,
        )
        return FileMeta(
            file_id=file_id,
            time_range=(t_min, t_max),
            num_rows=table.num_rows,
            file_size=os.path.getsize(path),
            level=level,
        )

    def _path(self, file_id: str) -> str:
        return os.path.join(self.sst_dir, f"{file_id}.parquet")


class SstReader:
    def __init__(self, sst_dir: str, schema: Schema):
        self.sst_dir = sst_dir
        self.schema = schema

    def path(self, meta: FileMeta) -> str:
        return self.path_for_id(meta.file_id)

    def path_for_id(self, file_id: str) -> str:
        return os.path.join(self.sst_dir, f"{file_id}.parquet")

    def prune_files(self, files: list[FileMeta], pred: ScanPredicate) -> list[FileMeta]:
        """File-level pruning on time range (whole-file min/max)."""
        if pred.time_range is None:
            return list(files)
        lo, hi = pred.time_range
        return [f for f in files if f.time_range[1] >= lo and f.time_range[0] < hi]

    def read(
        self,
        meta: FileMeta,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
    ) -> pa.Table:
        """Read one SST with row-group pruning + residual filter application."""
        pred = pred or ScanPredicate()
        pf = pq.ParquetFile(self.path(meta))
        ts_name = self.schema.time_index.name if self.schema.time_index else None
        groups = self._prune_row_groups(pf, pred, ts_name)
        if not groups:
            schema = pf.schema_arrow
            if columns:
                schema = pa.schema([schema.field(c) for c in columns])
            return schema.empty_table()
        table = pf.read_row_groups(groups, columns=columns, use_threads=True)
        # Parquet has no seconds timestamp unit: a timestamp("s") column comes
        # back as timestamp("ms").  Restore the declared logical type so
        # residual predicates (expressed in the native unit) compare correctly.
        if ts_name is not None and ts_name in table.column_names:
            want = self.schema.time_index.data_type.to_arrow()
            i = table.schema.get_field_index(ts_name)
            if table.schema.field(i).type != want:
                table = table.set_column(i, ts_name, pc.cast(table[ts_name], want))
        table = _apply_residual(table, pred, ts_name)
        return table

    def _prune_row_groups(self, pf: pq.ParquetFile, pred: ScanPredicate, ts_name) -> list[int]:
        md = pf.metadata
        if pred.time_range is None or ts_name is None:
            return list(range(md.num_row_groups))
        ts_idx = pf.schema_arrow.get_field_index(ts_name)
        if ts_idx < 0:
            return list(range(md.num_row_groups))  # no stats to prune on
        unit_ns = self.schema.time_index.data_type.timestamp_unit_ns()
        lo, hi = pred.time_range
        keep = []
        for g in range(md.num_row_groups):
            stats = md.row_group(g).column(ts_idx).statistics
            if stats is None or not stats.has_min_max:
                keep.append(g)
                continue
            g_min, g_max = _ts_to_int(stats.min, unit_ns), _ts_to_int(stats.max, unit_ns)
            if g_max >= lo and g_min < hi:
                keep.append(g)
        return keep


def _ts_to_int(v, unit_ns: int) -> int:
    """Convert a parquet stats value to the column's NATIVE timestamp unit.

    pyarrow surfaces timestamp stats as datetimes; predicates arrive in the
    column's own unit, so scale by the schema's unit (not hardcoded ms)."""
    if hasattr(v, "timestamp"):
        import calendar

        ns = calendar.timegm(v.utctimetuple()) * 1_000_000_000 + v.microsecond * 1000
        return ns // unit_ns
    return int(v)


def _apply_residual(table: pa.Table, pred: ScanPredicate, ts_name) -> pa.Table:
    """Apply exact time-range + pushed filters on the decoded table."""
    if table.num_rows == 0:
        return table
    mask = None
    if pred.time_range is not None and ts_name is not None and ts_name in table.column_names:
        lo, hi = pred.time_range
        ts = pc.cast(table[ts_name], pa.int64())
        mask = pc.and_(pc.greater_equal(ts, lo), pc.less(ts, hi))
    for name, op, value in pred.filters:
        if name not in table.column_names:
            continue
        col = table[name]
        if pa.types.is_dictionary(col.type):
            col = pc.cast(col, col.type.value_type)
        m = _cmp(col, op, value)
        mask = m if mask is None else pc.and_(mask, m)
    if mask is not None:
        table = table.filter(mask)
    return table


def _cmp(col, op: str, value):
    if op == "=":
        return pc.equal(col, value)
    if op == "!=":
        return pc.not_equal(col, value)
    if op == "<":
        return pc.less(col, value)
    if op == "<=":
        return pc.less_equal(col, value)
    if op == ">":
        return pc.greater(col, value)
    if op == ">=":
        return pc.greater_equal(col, value)
    if op == "in":
        return pc.is_in(col, value_set=pa.array(list(value)))
    raise ValueError(f"unknown filter op: {op}")
