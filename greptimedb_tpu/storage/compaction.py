"""Compaction: time-windowed merge of level-0 SSTs.

Role-equivalent of the reference's TWCS (time-windowed compaction strategy,
reference mito2/src/compaction/twcs.rs:45): SSTs are grouped by time window;
windows with more than `max_runs` level-0 files get their files k-way merged
(sort + dedup, last-write-wins) into one level-1 file.  Windowed merging
keeps write amplification bounded and SSTs window-aligned, which is also
what the TPU tile loader wants (one window = one contiguous tile range).
"""

from __future__ import annotations

from collections import defaultdict

import pyarrow as pa

from .memtable import _SEQ_COL, _sort_and_dedup
from .region import Region, _undict
from .sst import FileMeta


def pick_compaction(
    files: list[FileMeta],
    window_ms: int,
    max_active_runs: int = 4,
    max_inactive_runs: int = 1,
) -> list[list[FileMeta]]:
    """TWCS picker: group level-0 files by window; a window needing
    compaction returns its file group.  The most recent window (still being
    written, "active") tolerates more runs than older ("inactive") ones."""
    if not files:
        return []
    by_window: dict[int, list[FileMeta]] = defaultdict(list)
    for f in files:
        by_window[(f.time_range[0] // window_ms) * window_ms].append(f)
    active_window = max(by_window)
    picks = []
    for window, group in by_window.items():
        level0 = [f for f in group if f.level == 0]
        limit = max_active_runs if window == active_window else max_inactive_runs
        if len(level0) > limit:
            picks.append(level0)
    return picks


def infer_window_ms(files: list[FileMeta]) -> int:
    """Pick a TWCS window from data spread (reference twcs window inference):
    smallest bucket from a ladder that keeps total windows reasonable."""
    if not files:
        return 86_400_000
    lo = min(f.time_range[0] for f in files)
    hi = max(f.time_range[1] for f in files)
    span = max(hi - lo, 1)
    for w in (3_600_000, 7_200_000, 43_200_000, 86_400_000, 604_800_000):
        if span // w <= 64:
            return w
    return 604_800_000


def compact_files(region: Region, group: list[FileMeta]) -> FileMeta | None:
    """Merge one window's files: read, concat, sort(+dedup unless the
    region is append_mode — duplicates are semantically kept there), write
    level-1."""
    import numpy as np

    tables = []
    for meta in group:
        t = region.read_sst(meta)
        if t.num_rows:
            tables.append(_undict(t))
    if not tables:
        return None
    merged = pa.concat_tables(tables, promote_options="permissive")
    if region.merge_mode == "last_non_null" and not region.append_mode:
        # fieldwise merge is associative: the compacted row carries the
        # newest non-null value per field among its inputs, and future
        # reads fieldwise-merge it with newer sources exactly as if the
        # versions were still separate (reference dedup.rs LastNonNull)
        from .merge import _SEQ, _dedup_chunk

        key_cols = [c.name for c in region.schema.tag_columns()]
        if region.schema.time_index is not None:
            key_cols.append(region.schema.time_index.name)
        seq = pa.array(np.arange(merged.num_rows, dtype=np.int64))
        merged = merged.append_column(_SEQ, seq)
        merged = _dedup_chunk(merged, key_cols, region.schema, True, "last_non_null")
    else:
        seq = pa.array(np.arange(merged.num_rows, dtype=np.int64))
        merged = merged.append_column(_SEQ_COL, seq)
        merged = _sort_and_dedup(merged, region.schema, dedup=not region.append_mode)
        merged = merged.drop_columns([_SEQ_COL])
    return region.sst_writer.write(merged, level=1)


def compact_region(
    region: Region,
    window_ms: int | None = None,
    max_active_runs: int = 4,
    max_inactive_runs: int = 1,
) -> int:
    """Run one compaction round; returns number of window merges done.
    Serialized per region: the background scheduler and ADMIN
    compact_table must never pick the same group concurrently (the file
    list is re-read under the lock so a waiter sees the winner's edits)."""
    with region.compaction_lock:
        files = region.files()
        window = window_ms or infer_window_ms(files)
        picks = pick_compaction(files, window, max_active_runs, max_inactive_runs)
        done = 0
        for group in picks:
            new_meta = compact_files(region, group)
            adds = [new_meta] if new_meta is not None else []
            region.apply_compaction(adds, [f.file_id for f in group])
            done += 1
        return done
