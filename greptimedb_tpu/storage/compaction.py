"""Compaction: time-windowed merge of SSTs with sorted-run selection.

Role-equivalent of the reference's TWCS (time-windowed compaction strategy,
reference mito2/src/compaction/twcs.rs:45) plus its sorted-run math
(reference mito2/src/compaction/run.rs): SSTs are grouped by time window;
within a window, files partition into SORTED RUNS (sets of files whose
time ranges don't overlap).  Only windows whose RUN count exceeds the
limit compact, and only the cheapest runs merge — files that are already
disjoint never rewrite, which is what actually bounds write
amplification (the round-3 picker merged every level-0 file in an
over-populated window, re-merging disjoint data each round).

A global memory budget (reference compaction/memory_manager.rs) bounds
concurrent merge working sets: oversized groups split into sub-merges
that each fit the budget, and concurrent compactions serialize through
the budget gate.
"""

from __future__ import annotations

import threading
from collections import defaultdict

import pyarrow as pa

from .memtable import _SEQ_COL, _sort_and_dedup
from .region import Region, _undict
from .sst import FileMeta, interleaved_overlap_unsafe

# Parquet bytes expand roughly this much when decoded for the merge.
_DECODE_FACTOR = 4


def find_sorted_runs(files: list[FileMeta]) -> list[list[FileMeta]]:
    """Partition a window's files into sorted runs — each run holds files
    with pairwise-disjoint (inclusive) time ranges, greedily assigned in
    start order (interval-partitioning; reference run.rs
    find_sorted_runs).  len(result) == the window's run count."""
    runs: list[list[FileMeta]] = []
    for f in sorted(files, key=lambda m: m.time_range):
        for run in runs:
            if run[-1].time_range[1] < f.time_range[0]:
                run.append(f)
                break
        else:
            runs.append([f])
    return runs


def reduce_runs(runs: list[list[FileMeta]], target: int) -> list[FileMeta]:
    """Pick the files to merge so the window's run count drops to
    `target`: merging k runs into one removes k-1 runs, so take the
    k = len(runs) - target + 1 CHEAPEST runs by bytes (reference run.rs
    reduce_runs picks the minimal-penalty selection)."""
    if len(runs) <= target:
        return []
    k = len(runs) - target + 1
    by_cost = sorted(runs, key=lambda r: sum(f.file_size for f in r))
    return [f for run in by_cost[:k] for f in run]


def merge_seq_files(
    run: list[FileMeta], max_output_bytes: int
) -> list[list[FileMeta]]:
    """Within ONE sorted run, group consecutive SMALL files for merging
    (reference run.rs merge_seq_files): disjoint files don't need dedup,
    but dozens of tiny flush outputs cost read amplification — merge
    neighbors while the combined output stays under the size cap, which
    also bounds how often a byte can be rewritten (a file at the cap
    never joins another group)."""
    def balanced(group: list[FileMeta]) -> bool:
        # tiering guard: don't fold a tiny tail into a much larger file
        # every round (that rewrites the big file per flush — quadratic
        # write amp); wait until the smaller files together are worth it
        sizes = sorted(f.file_size for f in group)
        return len(group) > 1 and sizes[-1] <= 3 * max(sum(sizes[:-1]), 1)

    groups: list[list[FileMeta]] = []
    cur: list[FileMeta] = []
    cur_bytes = 0
    for f in sorted(run, key=lambda m: m.time_range):
        if f.file_size >= max_output_bytes:
            if balanced(cur):
                groups.append(cur)
            cur, cur_bytes = [], 0
            continue
        if cur and cur_bytes + f.file_size > max_output_bytes:
            if balanced(cur):
                groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(f)
        cur_bytes += f.file_size
    if balanced(cur):
        groups.append(cur)
    return groups


def pick_compaction(
    files: list[FileMeta],
    window_ms: int,
    max_active_runs: int = 4,
    max_inactive_runs: int = 1,
    max_output_bytes: int = 128 << 20,
) -> list[list[FileMeta]]:
    """TWCS picker: group files by window, count sorted runs per window,
    and for each over-run window emit the cheapest run set whose merge
    brings it back to the limit; within-limit windows still merge
    consecutive small files of a run (read-amplification control).  The
    most recent window (still being written, "active") tolerates more
    runs than older ("inactive") ones."""
    if not files:
        return []
    by_window: dict[int, list[FileMeta]] = defaultdict(list)
    for f in files:
        by_window[(f.time_range[0] // window_ms) * window_ms].append(f)
    active_window = max(by_window)
    picks = []
    for window, group in by_window.items():
        limit = max_active_runs if window == active_window else max_inactive_runs
        runs = find_sorted_runs(group)
        merge = reduce_runs(runs, limit)
        if len(merge) > 1:
            picks.append(merge)
            continue
        for run in runs:
            picks.extend(merge_seq_files(run, max_output_bytes))
    return picks


class CompactionMemoryManager:
    """Global budget for concurrent compaction working sets (reference
    mito2/src/compaction/memory_manager.rs): acquire blocks until the
    estimated decode footprint fits; a single estimate larger than the
    whole budget is admitted alone (it must run eventually — the split
    logic in compact_files keeps such groups rare)."""

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self._used = 0
        self._cv = threading.Condition()

    def acquire(self, est: int):
        with self._cv:
            while self._used > 0 and self._used + est > self.budget:
                self._cv.wait(timeout=30)
            self._used += est

    def release(self, est: int):
        with self._cv:
            self._used -= est
            self._cv.notify_all()


# process-wide gate, sized on first use (all engines share one budget:
# compaction memory is a machine resource, not a per-region one)
_memory_manager: CompactionMemoryManager | None = None
_memory_manager_lock = threading.Lock()


def _memory_gate(memory_mb: int) -> CompactionMemoryManager:
    global _memory_manager
    with _memory_manager_lock:
        if _memory_manager is None:
            _memory_manager = CompactionMemoryManager((memory_mb or 512) << 20)
        return _memory_manager


def overlap_clusters(group: list[FileMeta]) -> list[list[FileMeta]]:
    """Partition a merge group into clusters of transitively-overlapping
    files (sorted by start; a cluster breaks where the next file starts
    after everything seen so far ends).  Files that might hold the same
    (pk, ts) key are ALWAYS in one cluster — the unit that must dedup
    together."""
    out: list[list[FileMeta]] = []
    cur: list[FileMeta] = []
    cur_end = None
    for f in sorted(group, key=lambda m: m.time_range):
        if cur and f.time_range[0] > cur_end:
            out.append(cur)
            cur = []
            cur_end = None
        cur.append(f)
        cur_end = f.time_range[1] if cur_end is None else max(cur_end, f.time_range[1])
    if cur:
        out.append(cur)
    return out


def split_group_for_memory(
    group: list[FileMeta], budget_bytes: int
) -> list[list[FileMeta]]:
    """Split an oversized merge into sub-merges whose decode footprints
    fit the budget — along OVERLAP-CLUSTER boundaries only: duplicates
    of one key must dedup in a single merge (a split that separates two
    versions would let both survive into overlapping outputs and make
    last-write-wins order-dependent).  A single cluster larger than the
    budget merges alone (the memory gate admits oversized jobs solo).
    Each sub-merge output is therefore a genuine sorted-run piece."""
    out: list[list[FileMeta]] = []
    cur: list[FileMeta] = []
    cur_bytes = 0
    for cluster in overlap_clusters(group):
        est = sum(f.file_size for f in cluster) * _DECODE_FACTOR
        if cur and cur_bytes + est > budget_bytes:
            out.append(cur)
            cur, cur_bytes = [], 0
        cur.extend(cluster)
        cur_bytes += est
    if cur:
        if len(cur) == 1 and out:
            out[-1].extend(cur)
        else:
            out.append(cur)
    return out


def infer_window_ms(files: list[FileMeta]) -> int:
    """Pick a TWCS window from data spread (reference twcs window inference):
    smallest bucket from a ladder that keeps total windows reasonable."""
    if not files:
        return 86_400_000
    lo = min(f.time_range[0] for f in files)
    hi = max(f.time_range[1] for f in files)
    span = max(hi - lo, 1)
    for w in (3_600_000, 7_200_000, 43_200_000, 86_400_000, 604_800_000):
        if span // w <= 64:
            return w
    return 604_800_000


def compact_files(region: Region, group: list[FileMeta]) -> FileMeta | None:
    """Merge one window's files: read, concat, sort(+dedup unless the
    region is append_mode — duplicates are semantically kept there), write
    level-1."""
    import numpy as np

    tables = []
    for meta in group:
        t = region.read_sst(meta)
        if t.num_rows:
            tables.append(_undict(t))
    if not tables:
        return None
    merged = pa.concat_tables(tables, promote_options="permissive")
    if region.merge_mode == "last_non_null" and not region.append_mode:
        # fieldwise merge is associative: the compacted row carries the
        # newest non-null value per field among its inputs, and future
        # reads fieldwise-merge it with newer sources exactly as if the
        # versions were still separate (reference dedup.rs LastNonNull)
        from .merge import _SEQ, _dedup_chunk

        key_cols = [c.name for c in region.schema.tag_columns()]
        if region.schema.time_index is not None:
            key_cols.append(region.schema.time_index.name)
        seq = pa.array(np.arange(merged.num_rows, dtype=np.int64))
        merged = merged.append_column(_SEQ, seq)
        merged = _dedup_chunk(merged, key_cols, region.schema, True, "last_non_null")
    else:
        seq = pa.array(np.arange(merged.num_rows, dtype=np.int64))
        merged = merged.append_column(_SEQ_COL, seq)
        merged = _sort_and_dedup(merged, region.schema, dedup=not region.append_mode)
        merged = merged.drop_columns([_SEQ_COL])
    return region.sst_writer.write(merged, level=1)


def widen_for_order(
    sub: list[FileMeta], all_files: list[FileMeta], pos: dict[str, int]
) -> list[FileMeta]:
    """Grow an order-unsafe merge group to its safe closure: while a file
    outside the group both time-overlaps a member and sits between the
    group's manifest positions (interleaved_overlap_unsafe — one output
    position cannot rank it correctly), pull it INTO the group.  The
    closure always exists (at worst every file between min and max
    position joins) and merging it preserves last-write-wins, so refused
    picks never starve — they merge with their interleaved overwrites
    included instead of waiting for a round that may never come."""
    cur = {f.file_id: f for f in sub}
    changed = True
    while changed:
        changed = False
        ps = sorted(pos[fid] for fid in cur)
        lo, hi = ps[0], ps[-1]
        for x in all_files:
            if x.file_id in cur or not (lo < pos[x.file_id] < hi):
                continue
            if any(
                x.time_range[1] >= g.time_range[0]
                and x.time_range[0] <= g.time_range[1]
                for g in cur.values()
            ):
                cur[x.file_id] = x
                changed = True
    return sorted(cur.values(), key=lambda m: pos[m.file_id])


def compact_region(
    region: Region,
    window_ms: int | None = None,
    max_active_runs: int = 4,
    max_inactive_runs: int = 1,
    memory_mb: int = 512,
) -> int:
    """Run one compaction round; returns number of window merges done.
    Serialized per region: the background scheduler and ADMIN
    compact_table must never pick the same group concurrently (the file
    list is re-read under the lock so a waiter sees the winner's edits)."""
    with region.compaction_lock:
        files = region.files()
        window = window_ms or infer_window_ms(files)
        picks = pick_compaction(files, window, max_active_runs, max_inactive_runs)
        # dedup correctness depends on WRITE order: compact_files assigns
        # its dedup sequence by concat position, so every merge list must
        # follow manifest (flush) order — the pickers sort by cost/time
        # for SELECTION only
        manifest_pos = {f.file_id: i for i, f in enumerate(files)}
        gate = _memory_gate(memory_mb)
        done = 0
        for group in picks:
            # oversized merges split into budget-sized sub-merges; each
            # sub-merge output is a sorted run, so the next round's run
            # count still drops even when one pass can't merge everything
            for sub in split_group_for_memory(group, gate.budget):
                sub = sorted(sub, key=lambda m: manifest_pos[m.file_id])
                if not region.append_mode and interleaved_overlap_unsafe(
                    sub, files, manifest_pos
                ):
                    # a partial merge here would resurrect overwritten
                    # values — widen to the safe closure (pulls the
                    # interleaved overwrites into the merge) instead of
                    # skipping, so refused picks never starve
                    sub = widen_for_order(sub, files, manifest_pos)
                    if (
                        sum(f.file_size for f in sub) * _DECODE_FACTOR
                        > gate.budget
                    ):
                        continue  # closure too big this round
                est = min(
                    sum(f.file_size for f in sub) * _DECODE_FACTOR, gate.budget
                )
                gate.acquire(est)
                try:
                    new_meta = compact_files(region, sub)
                finally:
                    gate.release(est)
                adds = [new_meta] if new_meta is not None else []
                if region.apply_compaction(adds, [f.file_id for f in sub]):
                    done += 1
                elif new_meta is not None:
                    # commit refused (a flush interleaved an overlapping
                    # file mid-merge): the output must not enter the
                    # manifest — discard it and retry a later round
                    region.sst_reader.delete(new_meta.file_id)
        return done
