"""Shared-topic ("remote") WAL: the stateless-datanode failover log.

Role-equivalent of the reference's Kafka log store
(reference log-store/src/kafka/log_store.rs:70 — shared topics carrying
entries of many regions, a per-region index to demultiplex on replay,
high-watermark tracking, and WAL pruning that advances the topic trim
point once every region flushed past it, reference
meta-srv/src/procedure/wal_prune/ + RFC 2025-02-06-remote-wal-purge.md).

This build ships a file-backed implementation of the same interface (a
real Kafka backend needs network access, which this environment gates;
the config surface matches so a deployment can swap one in):

  * topic = a directory of CRC-framed segment files; the segment roll
    boundary is the pruning unit (Kafka's segment deletion);
  * entries carry (region_id, entry_id) so one topic serves many regions
    (reference entry_distributor/entry_reader demultiplexing);
  * `obsolete` only advances the region's flushed watermark — physical
    deletion happens in `prune`, segment-at-a-time, once every region
    with entries in the segment has flushed past them (exactly the
    reference's prune condition);
  * replay tolerates torn tails in the ACTIVE segment (crash mid-append)
    but refuses corruption in sealed segments.

Because topics live on shared storage, any datanode can replay any
region — the property that makes region failover possible without
copying data (reference: datanode replays from Kafka on open).

Frame layout (little-endian):
    [u32 payload_len][u32 crc32(payload)][u64 region_id][u64 entry_id][payload]
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import uuid
import zlib

import pyarrow as pa

from ..utils import fault_injection, metrics
from ..utils.errors import RetryLaterError, StorageError
from .wal import (
    GROUP_FLAG,
    WalEntry,
    _decode_batch,
    _decode_group,
    _encode_batch,
    _encode_group,
)

_FRAME = struct.Struct("<IIQQ")
SEGMENT_BYTES_DEFAULT = 4 << 20
# A follower registration older than this is ignored by prune: a follower
# that died (or stopped syncing) must not hold the shared log hostage
# forever.  Live followers refresh their position every sync round, which
# is orders of magnitude more frequent.
FOLLOWER_LW_TTL_S = 600.0


class SharedLogStore:
    """Topic-sharded shared append log on a common directory."""

    def __init__(self, root: str, fsync: bool = False, segment_bytes: int = SEGMENT_BYTES_DEFAULT):
        self.root = root
        self.fsync = fsync
        self.segment_bytes = segment_bytes
        self._lock = threading.RLock()
        self._active: dict[str, "_ActiveSegment"] = {}
        # region flushed watermarks (the per-region index the reference
        # keeps alongside Kafka), persisted so prune survives restarts
        self._flushed: dict[str, int] = {}
        os.makedirs(root, exist_ok=True)
        self._flushed_path = os.path.join(root, "flushed.json")
        if os.path.exists(self._flushed_path):
            with open(self._flushed_path) as f:
                self._flushed = {k: int(v) for k, v in json.load(f).items()}
        # follower replay low-watermarks: {region: {holder: [entry_id, ts]}}.
        # Followers register the entry id they have applied up to; prune
        # keeps min(flushed, follower_lw) so the tail a follower still
        # needs is never deleted under it.  Registrations expire after
        # follower_lw_ttl_s so a dead follower cannot pin the log forever.
        self.follower_lw_ttl_s = FOLLOWER_LW_TTL_S
        self._followers: dict[str, dict[str, list]] = {}
        # (region, holder) pairs registered THROUGH this instance — the only
        # entries this instance is authoritative for on reload; everything
        # else is read from disk, so another instance's unregister (follower
        # closed/promoted) deletes for real instead of being resurrected by
        # our stale in-memory copy on the next persist
        self._own: set[tuple[str, str]] = set()
        self._followers_path = os.path.join(root, "followers.json")
        self._reload_followers_locked()

    # ---- topics ------------------------------------------------------------
    def _topic_dir(self, topic: str) -> str:
        d = os.path.join(self.root, topic)
        os.makedirs(d, exist_ok=True)
        return d

    def topics(self) -> list[str]:
        return sorted(
            n for n in os.listdir(self.root) if os.path.isdir(os.path.join(self.root, n))
        )

    def _segments(self, topic: str) -> list[str]:
        d = self._topic_dir(topic)
        return sorted(n for n in os.listdir(d) if n.endswith(".seg"))

    def _active_segment(self, topic: str) -> "_ActiveSegment":
        seg = self._active.get(topic)
        if seg is None:
            d = self._topic_dir(topic)
            names = self._segments(topic)
            # adopt the newest on-disk segment if it has no sealed index yet;
            # decide before constructing so no stray empty segment is created
            if names and not os.path.exists(os.path.join(d, names[-1] + ".idx")):
                seg = _ActiveSegment.adopt(d, int(names[-1].split(".")[0]), self.fsync)
            else:
                base = int(names[-1].split(".")[0]) + 1 if names else 0
                seg = _ActiveSegment(d, base, self.fsync)
            self._active[topic] = seg
        return seg

    # ---- write -------------------------------------------------------------
    def append(self, topic: str, region_id: int, entry_id: int, batch: pa.RecordBatch):
        fault_injection.fire("wal.append", topic=topic, region_id=region_id)
        payload = _encode_batch(batch)
        header = _FRAME.pack(
            len(payload), zlib.crc32(memoryview(payload)), region_id, entry_id
        )
        self._write_frame(topic, (header, payload), region_id, entry_id)

    def append_group(
        self, topic: str, region_id: int, last_entry_id: int,
        batches: list[pa.RecordBatch],
    ):
        """One frame for a whole drain group (ids `last - n + 1 .. last`);
        the segment index records the REAL last id so pruning semantics
        are identical to frame-per-write."""
        fault_injection.fire("wal.append", topic=topic, region_id=region_id)
        head, ipc = _encode_group(batches)
        header = _FRAME.pack(
            len(head) + len(ipc),
            zlib.crc32(memoryview(ipc), zlib.crc32(head)),
            region_id, last_entry_id | GROUP_FLAG,
        )
        self._write_frame(topic, (header, head, ipc), region_id, last_entry_id)

    def _write_frame(self, topic: str, parts: tuple, region_id: int, entry_id: int):
        metrics.INGEST_WAL_BYTES.inc(sum(len(p) for p in parts))
        with self._lock:
            seg = self._active_segment(topic)
            seg.write(parts, region_id, entry_id)
            if seg.size >= self.segment_bytes:
                seg.seal()
                self._active.pop(topic, None)

    # ---- read --------------------------------------------------------------
    def read(self, topic: str, region_id: int, from_entry_id: int):
        """Yield WalEntry of `region_id` with id > from_entry_id, in order."""
        with self._lock:
            names = self._segments(topic)
            active = self._active.get(topic)
            if active is not None:
                active.flush()
        d = self._topic_dir(topic)
        for name in names:
            # sealed segments are strict: a roll with no new appends still
            # leaves a sealed tail that must parse cleanly
            sealed = os.path.exists(os.path.join(d, name + ".idx"))
            try:
                yield from self._read_segment(
                    os.path.join(d, name), region_id, from_entry_id,
                    tolerate_tail=not sealed,
                )
            except FileNotFoundError:
                # pruned concurrently — prune only removes fully-flushed
                # segments, so nothing this replay needs was lost
                continue

    def _read_segment(self, path: str, region_id: int, from_entry_id: int, tolerate_tail: bool):
        with open(path, "rb") as f:
            while True:
                # chaos hook: a test can run prune() at exactly this moment
                # to race segment deletion against a reader holding the file
                fault_injection.fire(
                    "wal.prune_during_read", path=path, region_id=region_id
                )
                header = f.read(_FRAME.size)
                if len(header) < _FRAME.size:
                    if header and not tolerate_tail:
                        raise self._sealed_read_error(path)
                    return
                length, crc, rid, entry_id = _FRAME.unpack(header)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    if not tolerate_tail:
                        raise self._sealed_read_error(path)
                    return  # torn tail of the active segment — stop here
                if rid != region_id:
                    continue
                if entry_id & GROUP_FLAG:
                    last = entry_id & ~GROUP_FLAG
                    subs = _decode_group(payload)
                    first = last - len(subs) + 1
                    for i, b in enumerate(subs):
                        if first + i > from_entry_id:
                            yield WalEntry(first + i, b)
                elif entry_id > from_entry_id:
                    yield WalEntry(entry_id, _decode_batch(payload))

    @staticmethod
    def _sealed_read_error(path: str) -> Exception:
        """A sealed segment is immutable after the .idx marker lands, so a
        short/CRC-failing frame in one means either real corruption or the
        segment was PRUNED under this reader (the platform let the unlink
        orphan the open handle's view).  The pruned case is retryable by
        contract — the replay restarts from the caller's watermark and the
        pruned entries were flushed/covered anyway — and must never surface
        as a mid-frame decode crash."""
        if not os.path.exists(path):
            return RetryLaterError(
                f"wal segment {path} pruned during read; retry the replay"
            )
        return StorageError(f"corrupt sealed wal segment {path}")

    def last_entry_id(self, topic: str, region_id: int) -> int:
        last = 0
        for entry in self.read(topic, region_id, 0):
            last = entry.entry_id
        return max(last, self._flushed.get(str(region_id), 0))

    # ---- flush watermarks & pruning ---------------------------------------
    def _reload_flushed_locked(self):
        """Merge watermarks other store instances persisted (multiple
        datanodes share this directory like they'd share a Kafka cluster;
        max-merge keeps the map monotonic under racy writers)."""
        if os.path.exists(self._flushed_path):
            try:
                with open(self._flushed_path) as f:
                    on_disk = json.load(f)
            except ValueError:
                return
            for k, v in on_disk.items():
                if int(v) > self._flushed.get(k, 0):
                    self._flushed[k] = int(v)

    def set_flushed(self, region_id: int, entry_id: int):
        with self._lock:
            key = str(region_id)
            if self._flushed.get(key, 0) >= entry_id:
                return
            self._reload_flushed_locked()
            self._flushed[key] = max(self._flushed.get(key, 0), entry_id)
            tmp = self._flushed_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._flushed, f)
            os.replace(tmp, self._flushed_path)

    def flushed(self, region_id: int) -> int:
        return self._flushed.get(str(region_id), 0)

    # ---- follower replay low-watermarks ------------------------------------
    def _reload_followers_locked(self):
        """Adopt the registrations other store instances persisted (a
        follower datanode registers through ITS store object; the leader's
        prune must see it).  Disk is authoritative for holders this
        instance did not register — including DELETIONS: an unregister
        persisted elsewhere must not be resurrected from our stale
        in-memory copy.  For our OWN holders the newest timestamp wins."""
        try:
            with open(self._followers_path) as f:
                on_disk = json.load(f)
        except (OSError, ValueError):
            on_disk = {}
        merged: dict[str, dict[str, list]] = {
            rid: {
                holder: [int(entry_id), float(ts)]
                for holder, (entry_id, ts) in holders.items()
            }
            for rid, holders in on_disk.items()
        }
        for rid, holder in self._own:
            val = self._followers.get(rid, {}).get(holder)
            if val is None:
                continue
            cur = merged.setdefault(rid, {}).get(holder)
            if cur is None or val[1] >= cur[1]:
                merged[rid][holder] = val
        self._followers = merged

    def _persist_followers_locked(self):
        tmp = self._followers_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._followers, f)
        os.replace(tmp, self._followers_path)

    def register_follower(self, region_id: int, holder: str, entry_id: int):
        """Record that follower `holder` has replayed region `region_id` up
        to `entry_id`: prune keeps min(flushed, follower_lw) so the tail
        this follower still needs is never deleted under it."""
        with self._lock:
            key = (str(region_id), holder)
            if key in self._own:
                cur = self._followers.get(key[0], {}).get(holder)
                # unchanged position + still-fresh stamp: skip the whole
                # reload-merge-rewrite cycle.  follower_sync re-registers
                # every round, so an idle cluster would otherwise rewrite
                # the shared followers.json once per region per interval;
                # refreshing only past half the TTL keeps the on-disk
                # stamp at most ttl/2 stale — liveness still holds.
                if (cur is not None and cur[0] == int(entry_id)
                        and time.time() - cur[1] < self.follower_lw_ttl_s / 2):
                    return
            self._own.add(key)
            self._reload_followers_locked()
            self._followers.setdefault(str(region_id), {})[holder] = [
                int(entry_id), time.time(),
            ]
            self._persist_followers_locked()

    def unregister_follower(self, region_id: int, holder: str):
        """Drop a follower's registration (it closed or was promoted) so
        it stops holding segments back."""
        with self._lock:
            self._own.discard((str(region_id), holder))
            self._reload_followers_locked()
            holders = self._followers.get(str(region_id))
            if holders and holder in holders:
                del holders[holder]
                if not holders:
                    del self._followers[str(region_id)]
                self._persist_followers_locked()

    def _follower_lw_locked(self, region_key: str) -> int | None:
        """Minimum replay position over FRESH follower registrations, or
        None when no live follower constrains this region."""
        holders = self._followers.get(region_key)
        if not holders:
            return None
        cutoff = time.time() - self.follower_lw_ttl_s
        fresh = [e for e, ts in holders.values() if ts >= cutoff]
        return min(fresh) if fresh else None

    def prune(self, topic: str) -> int:
        """Delete sealed segments whose every entry is flushed AND replayed
        past by every live follower; returns the number of segments removed
        (the reference's wal_prune procedure advances Kafka's trim point
        under the flushed condition; the follower low-watermark is what
        keeps bounded-staleness replicas from losing the tail they are
        about to replay)."""
        removed = 0
        d = self._topic_dir(topic)
        with self._lock:
            self._reload_flushed_locked()  # see other datanodes' flush marks
            self._reload_followers_locked()  # and followers' replay marks
            for name in self._segments(topic):
                idx_path = os.path.join(d, name + ".idx")
                if not os.path.exists(idx_path):
                    break  # active segment — nothing newer is prunable either
                with open(idx_path) as f:
                    max_by_region = json.load(f)
                held = False
                for rid, max_id in max_by_region.items():
                    if self._flushed.get(rid, 0) < max_id:
                        held = True
                        break
                    lw = self._follower_lw_locked(rid)
                    if lw is not None and lw < max_id:
                        metrics.WAL_PRUNE_HELD_TOTAL.inc()
                        held = True
                        break
                if held:
                    break  # keep order: never punch holes in the log
                os.remove(os.path.join(d, name))
                os.remove(idx_path)
                removed += 1
        return removed

    def prune_all(self) -> int:
        return sum(self.prune(t) for t in self.topics())

    def close(self):
        with self._lock:
            for seg in self._active.values():
                seg.flush()
                seg.close()
            self._active.clear()


class _ActiveSegment:
    """The topic's open segment; sealing writes a {region: max_entry} index
    sidecar that prune uses (the reference tracks the same per-region max
    offsets in its Kafka index)."""

    def __init__(self, topic_dir: str, base: int, fsync: bool):
        self.path = os.path.join(topic_dir, f"{base:020d}.seg")
        self.fsync = fsync
        self._file = open(self.path, "ab")
        self.size = os.path.getsize(self.path)
        self.max_by_region: dict[str, int] = {}

    @classmethod
    def adopt(cls, topic_dir: str, base: int, fsync: bool) -> "_ActiveSegment":
        """Reopen an unsealed segment after restart, rebuilding its index
        from the frames (torn tail tolerated)."""
        seg = cls(topic_dir, base, fsync)
        with open(seg.path, "rb") as f:
            while True:
                header = f.read(_FRAME.size)
                if len(header) < _FRAME.size:
                    break
                length, crc, rid, entry_id = _FRAME.unpack(header)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                key = str(rid)
                # group frames carry the group's LAST id (flagged)
                seg.max_by_region[key] = max(
                    seg.max_by_region.get(key, 0), entry_id & ~GROUP_FLAG
                )
        return seg

    def write(self, frame, region_id: int, entry_id: int):
        """`frame` is bytes or a tuple of buffer parts (header, payload
        …) written back to back — writers avoid payload-sized concat
        copies this way."""
        parts = frame if isinstance(frame, tuple) else (frame,)
        for p in parts:
            self._file.write(p)
        self._file.flush()
        if self.fsync:
            os.fsync(self._file.fileno())
        self.size += sum(len(p) for p in parts)
        key = str(region_id)
        self.max_by_region[key] = max(self.max_by_region.get(key, 0), entry_id)

    def flush(self):
        self._file.flush()

    def seal(self):
        # The .idx sidecar marks the segment sealed, and replay treats sealed
        # segments strictly — so the data must be durable BEFORE the marker
        # appears, even when per-write fsync is off (one fsync per roll).
        self.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        with open(self.path + ".idx.tmp", "w") as f:
            json.dump(self.max_by_region, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(self.path + ".idx.tmp", self.path + ".idx")

    def close(self):
        try:
            self._file.close()
        except ValueError:
            pass


class RemoteRegionWal:
    """RegionWal-compatible adapter over a SharedLogStore topic
    (the reference's `Wal<KafkaLogStore>`)."""

    def __init__(self, store: SharedLogStore, topic: str, region_id: int):
        self.store = store
        self.topic = topic
        self.region_id = region_id
        self._lock = threading.Lock()
        self.last_entry_id = store.last_entry_id(topic, region_id)
        # per-instance holder token for follower low-watermark registration
        # (leader and follower engines hold distinct instances over the
        # same shared directory, like they'd hold distinct Kafka consumers)
        self._holder = uuid.uuid4().hex[:12]
        self._registered = False

    def advance_to(self, entry_id: int):
        with self._lock:
            self.last_entry_id = max(self.last_entry_id, entry_id)

    def append(self, batch: pa.RecordBatch) -> int:
        with self._lock:
            entry_id = self.last_entry_id + 1
            self.store.append(self.topic, self.region_id, entry_id, batch)
            self.last_entry_id = entry_id
        metrics.INGEST_WAL_FRAMES.inc()
        return entry_id

    def append_group(self, batches: list[pa.RecordBatch]) -> list[int]:
        """Group-commit twin of RegionWal.append_group over the shared
        topic: one frame, per-write entry ids."""
        if len(batches) == 1:
            return [self.append(batches[0])]
        with self._lock:
            first = self.last_entry_id + 1
            last = self.last_entry_id + len(batches)
            self.store.append_group(self.topic, self.region_id, last, batches)
            self.last_entry_id = last
        metrics.INGEST_WAL_FRAMES.inc()
        metrics.INGEST_GROUP_FRAMES.inc()
        metrics.INGEST_GROUP_WRITES.inc(len(batches))
        return list(range(first, last + 1))

    def replay(self, from_entry_id: int):
        yield from self.store.read(self.topic, self.region_id, from_entry_id)

    def obsolete(self, up_to_entry_id: int):
        """Advance the flushed watermark only — the shared topic is pruned
        segment-wise by the wal-prune procedure (reference logstore
        obsolete on Kafka likewise only moves indexes)."""
        self.store.set_flushed(self.region_id, up_to_entry_id)

    # ---- follower replay position (bounded-staleness replicas) -------------
    def register_replay_position(self, entry_id: int):
        """A follower tailing this log records how far it has applied;
        prune keeps every entry a registered follower still needs."""
        self.store.register_follower(self.region_id, self._holder, entry_id)
        self._registered = True

    def release_replay_position(self):
        """Stop constraining prune (follower closed or was promoted)."""
        if self._registered:
            self.store.unregister_follower(self.region_id, self._holder)
            self._registered = False

    def close(self):
        self.release_replay_position()  # topic files are owned by the store


class RemoteWalManager:
    """WalManager facade over shared topics (reference topic_region mapping:
    region -> topic by modulo, common/meta/src/key/topic_region.rs)."""

    def __init__(self, wal_dir: str, fsync: bool = False, num_topics: int = 4,
                 segment_bytes: int = SEGMENT_BYTES_DEFAULT):
        self.store = SharedLogStore(wal_dir, fsync=fsync, segment_bytes=segment_bytes)
        self.num_topics = max(1, num_topics)
        self._regions: dict[int, RemoteRegionWal] = {}
        self._lock = threading.Lock()

    def topic_of(self, region_id: int) -> str:
        return f"topic_{region_id % self.num_topics}"

    def region_wal(self, region_id: int) -> RemoteRegionWal:
        with self._lock:
            wal = self._regions.get(region_id)
            if wal is None:
                wal = RemoteRegionWal(self.store, self.topic_of(region_id), region_id)
                self._regions[region_id] = wal
            return wal

    def drop_region(self, region_id: int):
        with self._lock:
            wal = self._regions.pop(region_id, None)
        if wal is not None:
            # everything this region wrote becomes prunable
            self.store.set_flushed(region_id, wal.last_entry_id)

    def prune(self) -> int:
        return self.store.prune_all()

    def close(self):
        with self._lock:
            self._regions.clear()
        self.store.close()
