"""Time-partitioned columnar memtable.

Role-equivalent of the reference's memtables (reference
src/mito2/src/memtable/): buffered writes live in memory until flush.  The
reference keeps three builders (partition-tree, per-series, bulk); we keep a
single append-mode columnar memtable partitioned by time window (the
reference's `time_partition.rs` behavior), with last-write-wins dedup applied
on read/flush by a stable sort over (primary key, time index, sequence).
This matches the reference's `DedupReader` last-row semantics
(mito2/src/read/dedup.rs) while keeping ingestion append-only — the shape
that flushes to TPU-friendly columnar tiles.
"""

from __future__ import annotations

import threading

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..datatypes.schema import Schema

_SEQ_COL = "__seq"


def _partition_starts(ts: np.ndarray, window_ms: int) -> np.ndarray:
    return (ts // window_ms) * window_ms


class Memtable:
    """Append-only columnar buffer with time-window partitioning."""

    def __init__(self, schema: Schema, time_partition_ms: int = 86_400_000):
        self.schema = schema
        self.time_partition_ms = time_partition_ms
        self._chunks: list[pa.RecordBatch] = []
        self._seqs: list[np.ndarray] = []
        self._rows = 0
        self._bytes = 0
        self._lock = threading.Lock()
        self._min_ts: int | None = None
        self._max_ts: int | None = None

    # ---- write ------------------------------------------------------------
    def write(self, batch: pa.RecordBatch, sequence: int):
        """Append a batch stamped with a monotonically increasing sequence.

        The sequence plays the role of the reference's per-write `SequenceNumber`
        (store-api) — dedup keeps the highest sequence for identical
        (primary key, timestamp) rows.
        """
        ts_col = self.schema.time_index
        with self._lock:
            self._chunks.append(batch)
            self._seqs.append(np.full(batch.num_rows, sequence, dtype=np.int64))
            self._rows += batch.num_rows
            self._bytes += batch.nbytes
            if ts_col is not None and batch.num_rows:
                ts = batch.column(batch.schema.get_field_index(ts_col.name))
                mm = pc.min_max(ts)  # one pass, not two
                lo = mm["min"].cast(pa.int64()).as_py()
                hi = mm["max"].cast(pa.int64()).as_py()
                self._min_ts = lo if self._min_ts is None else min(self._min_ts, lo)
                self._max_ts = hi if self._max_ts is None else max(self._max_ts, hi)

    # ---- stats ------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._rows

    @property
    def memory_usage(self) -> int:
        return self._bytes

    def is_empty(self) -> bool:
        return self._rows == 0

    def time_range(self) -> tuple[int, int] | None:
        if self._min_ts is None:
            return None
        return (self._min_ts, self._max_ts)

    # ---- read -------------------------------------------------------------
    def to_table(self, dedup: bool = True) -> pa.Table:
        """Materialize buffered rows sorted by (pk, ts), last write wins."""
        with self._lock:
            if not self._chunks:
                return self.schema.to_arrow().empty_table()
            table = pa.Table.from_batches(self._chunks, schema=self._chunks[0].schema)
            seq = pa.array(np.concatenate(self._seqs))
        table = table.append_column(_SEQ_COL, seq)
        table = _sort_and_dedup(table, self.schema, dedup=dedup)
        return table.drop_columns([_SEQ_COL])

    def scan(self, time_range: tuple[int, int] | None = None, dedup: bool = True) -> pa.Table:
        table = self.to_table(dedup=dedup)
        if time_range is not None and self.schema.time_index is not None:
            lo, hi = time_range
            ts_name = self.schema.time_index.name
            ts = pc.cast(table[ts_name], pa.int64())
            mask = pc.and_(pc.greater_equal(ts, lo), pc.less(ts, hi))
            table = table.filter(mask)
        return table

    def split_by_time_partition(self, dedup: bool = True) -> list[tuple[int, pa.Table]]:
        """Split into (window_start_ms, rows) — flush writes one SST per window
        so SSTs stay window-aligned for TWCS (reference
        mito2/src/memtable/time_partition.rs)."""
        table = self.to_table(dedup=dedup)
        ts_col = self.schema.time_index
        if table.num_rows == 0:
            return []
        if ts_col is None:
            return [(0, table)]
        ts = pc.cast(table[ts_col.name], pa.int64()).to_numpy(zero_copy_only=False)
        starts = _partition_starts(ts, self.time_partition_ms)
        out = []
        for start in np.unique(starts):
            mask = starts == start
            out.append((int(start), table.filter(pa.array(mask))))
        return out


def _sort_and_dedup(table: pa.Table, schema: Schema, dedup: bool) -> pa.Table:
    """Stable sort by (pk..., ts, seq) then keep the last row per (pk..., ts)."""
    keys = [c.name for c in schema.tag_columns()]
    ts_col = schema.time_index
    if ts_col is not None:
        keys.append(ts_col.name)
    if not keys:
        return table
    fast = _key_codes(table, keys)
    if fast is not None:
        # Vectorized fast path (the flush/scan hot shape: string tags +
        # int/timestamp keys): rank-encode each key column to int64 codes
        # ordering EXACTLY like arrow's ascending nulls-last comparator
        # (the small per-column dictionary is ranked BY arrow), then one
        # stable np.lexsort over the codes — string comparisons happen
        # O(distinct values), not O(rows log rows).
        msf, eq_cols = fast
        seq = np.asarray(
            table[_SEQ_COL].combine_chunks(), dtype=np.int64
        )
        order = np.lexsort(tuple(reversed(msf + [seq])))
        table = table.take(pa.array(order))
        if not dedup or table.num_rows <= 1:
            return table
        n = table.num_rows
        same = np.ones(n - 1, dtype=bool)
        for arr in eq_cols:
            a = arr[order]
            same &= a[:-1] == a[1:]
        keep = np.ones(n, dtype=bool)
        keep[:-1] = ~same
        return table.filter(pa.array(keep))
    sort_keys = [(k, "ascending") for k in keys] + [(_SEQ_COL, "ascending")]
    idx = pc.sort_indices(table, sort_keys=sort_keys)
    table = table.take(idx)
    if not dedup or table.num_rows <= 1:
        return table
    # Keep the LAST row of each equal-key run (highest sequence).
    n = table.num_rows
    same = np.ones(n - 1, dtype=bool)
    for k in keys:
        col = table[k].combine_chunks()
        arr = col.to_numpy(zero_copy_only=False)
        a, b = arr[:-1], arr[1:]
        if arr.dtype == object:
            eq = np.array([x == y for x, y in zip(a, b)], dtype=bool)
        else:
            eq = (a == b) | (_isnan(a) & _isnan(b))
        same &= eq
    keep = np.ones(n, dtype=bool)
    keep[:-1] = ~same  # row i dropped if identical key to row i+1 (later seq)
    return table.filter(pa.array(keep))


def _key_codes(table: pa.Table, keys: list[str]):
    """int64 code arrays ordering identically to arrow's ascending
    nulls-last sort over `keys`, or None when a column's type is not
    covered (floats etc. keep the arrow sort path).

    Returns (msf, eq_cols): `msf` = most-significant-first lexsort keys
    (a nullable int column contributes [is_null, value] so nulls land
    last); `eq_cols` = one pair-compare array per contributed key (code
    equality <=> arrow value equality, nulls equal each other — the
    dedup adjacency contract of the legacy loop)."""
    msf: list[np.ndarray] = []
    eq_cols: list[np.ndarray] = []
    for k in keys:
        col = table[k]
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        t = col.type
        if pa.types.is_dictionary(t):
            col = pc.cast(col, t.value_type)
            t = col.type
        if (pa.types.is_string(t) or pa.types.is_large_string(t)
                or pa.types.is_binary(t)):
            enc = pc.dictionary_encode(col)
            d = enc.dictionary
            # rank the (small) dictionary with ARROW's own comparator so
            # the code order is bit-identical to its string sort
            order = np.asarray(pc.sort_indices(d), dtype=np.int64)
            ranks = np.empty(len(d), dtype=np.int64)
            ranks[order] = np.arange(len(d), dtype=np.int64)
            idxs = np.asarray(pc.fill_null(enc.indices, -1), dtype=np.int64)
            if len(d) == 0:  # all-null column: one code for everything
                codes = np.zeros(len(idxs), dtype=np.int64)
            else:
                codes = np.where(
                    idxs >= 0,
                    ranks[np.clip(idxs, 0, len(d) - 1)],
                    np.int64(len(d)),  # nulls past every rank = nulls last
                )
            msf.append(codes)
            eq_cols.append(codes)
        elif (pa.types.is_integer(t) or pa.types.is_timestamp(t)
                or pa.types.is_boolean(t)):
            try:
                vals = np.asarray(
                    pc.fill_null(pc.cast(col, pa.int64()), 0), dtype=np.int64
                )
            except pa.ArrowInvalid:
                # uint64 values past 2^63 don't fit the code space —
                # keep the arrow sort path for this table
                return None
            if col.null_count:
                isnull = np.asarray(pc.is_null(col), dtype=np.int64)
                msf.append(isnull)  # nulls after values (ascending 0 < 1)
                msf.append(vals)
                # (value, is_null) pairs compare equal exactly when the
                # logical values do (null == null, null != 0)
                eq_cols.append(vals)
                eq_cols.append(isnull)
            else:
                msf.append(vals)
                eq_cols.append(vals)
        else:
            return None
    return msf, eq_cols


def _isnan(a: np.ndarray) -> np.ndarray:
    if np.issubdtype(a.dtype, np.floating):
        return np.isnan(a)
    return np.zeros(len(a), dtype=bool)


class TimeSeriesMemtable(Memtable):
    """Per-series write accumulation (reference
    mito2/src/memtable/time_series.rs `TimeSeriesMemtable`: one vector
    builder per primary key).  Batches are split by series at WRITE time
    into per-series buckets, so flush/scan concatenates pre-grouped runs
    instead of sorting the whole buffer — the right trade when series
    count is small relative to rows (dense scrape workloads), and the
    shape series_scan-style readers want.

    Read-side semantics are identical to the base memtable: sorted by
    (pk, ts), last-write-wins on (pk, ts) ties.
    """

    def __init__(self, schema: Schema, time_partition_ms: int = 86_400_000):
        super().__init__(schema, time_partition_ms)
        self._series: dict[tuple, list[pa.RecordBatch]] = {}
        self._series_seqs: dict[tuple, list[np.ndarray]] = {}
        self._pk_names = [c.name for c in schema.tag_columns()]

    def write(self, batch: pa.RecordBatch, sequence: int):
        ts_col = self.schema.time_index
        with self._lock:
            if not self._pk_names:
                key = ()
                self._series.setdefault(key, []).append(batch)
                self._series_seqs.setdefault(key, []).append(
                    np.full(batch.num_rows, sequence, dtype=np.int64)
                )
            else:
                # group rows by series key via dictionary codes (vectorized;
                # the reference hashes encoded primary keys the same way)
                import pyarrow.compute as _pc

                codes = None
                dicts = []
                for name in self._pk_names:
                    col = batch.column(batch.schema.get_field_index(name))
                    enc = _pc.dictionary_encode(col)
                    idxs = np.asarray(enc.indices, dtype=np.int64)
                    dicts.append(enc.dictionary.to_pylist())
                    codes = idxs if codes is None else codes * len(dicts[-1]) + idxs
                for code in np.unique(codes):
                    mask = codes == code
                    sub = batch.filter(pa.array(mask))
                    first = int(np.flatnonzero(mask)[0])
                    key = tuple(
                        batch.column(batch.schema.get_field_index(n))[first].as_py()
                        for n in self._pk_names
                    )
                    self._series.setdefault(key, []).append(sub)
                    self._series_seqs.setdefault(key, []).append(
                        np.full(sub.num_rows, sequence, dtype=np.int64)
                    )
            self._rows += batch.num_rows
            self._bytes += batch.nbytes
            if ts_col is not None and batch.num_rows:
                ts = batch.column(batch.schema.get_field_index(ts_col.name))
                mm = pc.min_max(ts)  # one pass, not two
                lo = mm["min"].cast(pa.int64()).as_py()
                hi = mm["max"].cast(pa.int64()).as_py()
                self._min_ts = lo if self._min_ts is None else min(self._min_ts, lo)
                self._max_ts = hi if self._max_ts is None else max(self._max_ts, hi)

    def to_table(self, dedup: bool = True) -> pa.Table:
        """Concatenate series in key order; each series sorts only its own
        rows by (ts, seq) — no global sort."""
        with self._lock:
            if not self._series:
                return self.schema.to_arrow().empty_table()
            items = sorted(self._series.items(), key=lambda kv: _series_sort_key(kv[0]))
            parts = []
            for key, chunks in items:
                t = pa.Table.from_batches(chunks, schema=chunks[0].schema)
                seq = pa.array(np.concatenate(self._series_seqs[key]))
                t = t.append_column(_SEQ_COL, seq)
                parts.append(t)
        out = []
        for t in parts:
            out.append(_sort_and_dedup_series(t, self.schema, dedup=dedup))
        merged = pa.concat_tables(out)
        return merged.drop_columns([_SEQ_COL])

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)


def _series_sort_key(key: tuple):
    # None sorts first, mirroring arrow's default null placement in the
    # base memtable's global sort
    return tuple((v is not None, v) for v in key)


def _sort_and_dedup_series(table: pa.Table, schema: Schema, dedup: bool) -> pa.Table:
    """Per-series (ts, seq) sort + last-write-wins on ts ties."""
    ts_col = schema.time_index
    if ts_col is None:
        return table
    idx = pc.sort_indices(
        table, sort_keys=[(ts_col.name, "ascending"), (_SEQ_COL, "ascending")]
    )
    table = table.take(idx)
    if not dedup or table.num_rows <= 1:
        return table
    ts = pc.cast(table[ts_col.name], pa.int64()).to_numpy(zero_copy_only=False)
    keep = np.ones(len(ts), dtype=bool)
    keep[:-1] = ts[:-1] != ts[1:]
    return table.filter(pa.array(keep))


class PartitionTreeMemtable(Memtable):
    """Primary-key-sharded buffers (reference
    mito2/src/memtable/partition_tree.rs `PartitionTreeMemtable`: a
    dictionary/shard tree over encoded primary keys).  Rows are routed to
    one of `num_shards` buckets by a hash of the pk columns at WRITE time;
    reads sort each (small) shard independently and merge — bounding sort
    working sets for high-cardinality key spaces where the per-series
    variant would explode into millions of tiny buckets.

    Read semantics identical to the base memtable: (pk, ts) sorted,
    last-write-wins."""

    def __init__(
        self, schema: Schema, time_partition_ms: int = 86_400_000, num_shards: int = 8
    ):
        super().__init__(schema, time_partition_ms)
        self.num_shards = num_shards
        self._shards: list[list[pa.RecordBatch]] = [[] for _ in range(num_shards)]
        self._shard_seqs: list[list[np.ndarray]] = [[] for _ in range(num_shards)]
        self._pk_names = [c.name for c in schema.tag_columns()]

    def write(self, batch: pa.RecordBatch, sequence: int):
        ts_col = self.schema.time_index
        with self._lock:
            if not self._pk_names:
                shard_ids = np.zeros(batch.num_rows, dtype=np.int64)
            else:
                import pyarrow.compute as _pc

                h = np.zeros(batch.num_rows, dtype=np.uint64)
                for name in self._pk_names:
                    col = batch.column(batch.schema.get_field_index(name))
                    enc = _pc.dictionary_encode(col)
                    # nulls encode as null indices: route them to a fixed
                    # salt instead of letting the uint64 cast wrap into an
                    # out-of-bounds gather
                    idxs = np.asarray(
                        _pc.fill_null(enc.indices, -1), dtype=np.int64
                    )
                    vals = enc.dictionary
                    salts = np.asarray(
                        [hash(v) & 0xFFFFFFFF for v in vals.to_pylist()] or [0],
                        dtype=np.uint64,
                    )
                    picked = np.where(
                        idxs >= 0,
                        salts[np.clip(idxs, 0, len(salts) - 1)],
                        np.uint64(0x9E3779B9),
                    )
                    h = h * np.uint64(1099511628211) + picked
                shard_ids = (h % np.uint64(self.num_shards)).astype(np.int64)
            for sid in np.unique(shard_ids):
                mask = shard_ids == sid
                sub = batch.filter(pa.array(mask))
                self._shards[int(sid)].append(sub)
                self._shard_seqs[int(sid)].append(
                    np.full(sub.num_rows, sequence, dtype=np.int64)
                )
            self._rows += batch.num_rows
            self._bytes += batch.nbytes
            if ts_col is not None and batch.num_rows:
                ts = batch.column(batch.schema.get_field_index(ts_col.name))
                mm = pc.min_max(ts)  # one pass, not two
                lo = mm["min"].cast(pa.int64()).as_py()
                hi = mm["max"].cast(pa.int64()).as_py()
                self._min_ts = lo if self._min_ts is None else min(self._min_ts, lo)
                self._max_ts = hi if self._max_ts is None else max(self._max_ts, hi)

    def to_table(self, dedup: bool = True) -> pa.Table:
        with self._lock:
            parts = []
            for sid in range(self.num_shards):
                if not self._shards[sid]:
                    continue
                t = pa.Table.from_batches(
                    self._shards[sid], schema=self._shards[sid][0].schema
                )
                t = t.append_column(
                    _SEQ_COL, pa.array(np.concatenate(self._shard_seqs[sid]))
                )
                parts.append(t)
        if not parts:
            return self.schema.to_arrow().empty_table()
        # each shard is small; the final concat needs a global sort
        # only across shard boundaries — cheaper: sort the concat of
        # per-shard-sorted runs (timsort-friendly) in one pass
        table = pa.concat_tables(parts, promote_options="permissive")
        table = _sort_and_dedup(table, self.schema, dedup=dedup)
        return table.drop_columns([_SEQ_COL])


class BulkMemtable(Memtable):
    """Bulk-ingestion parts (reference mito2/src/memtable/bulk/ +
    simple_bulk_memtable): large ingested batches are kept as immutable
    zero-copy PARTS — no per-write re-encoding or splitting — and only
    read-time materialization pays for sorting.  The right shape for
    Flight DoPut bulk loads where batches arrive large and pre-sorted."""

    # identical storage to the base memtable (whole-batch append, no
    # copies); the distinction the reference draws — write path does NO
    # per-row work — already holds, so this subclass exists to (a) name
    # the contract and (b) skip the dedup sort when parts declare
    # themselves internally sorted and non-overlapping.

    def to_table(self, dedup: bool = True) -> pa.Table:
        with self._lock:
            if not self._chunks:
                return self.schema.to_arrow().empty_table()
            if len(self._chunks) == 1 and not dedup:
                t = pa.Table.from_batches(self._chunks)
                # zero-copy only when the part IS (pk, ts)-sorted — the
                # streaming merge consumes memtable output as a sorted run
                if _is_key_sorted(t, self.schema):
                    return t
        return super().to_table(dedup=dedup)


def _is_key_sorted(t: pa.Table, schema: Schema) -> bool:
    """O(n) lexicographic non-decreasing check over (pk..., ts)."""
    keys = [c.name for c in schema.tag_columns()]
    if schema.time_index is not None:
        keys.append(schema.time_index.name)
    n = t.num_rows
    if n <= 1 or not keys:
        return True
    undecided = np.ones(n - 1, dtype=bool)  # adjacent pairs equal so far
    ok = np.ones(n - 1, dtype=bool)
    for name in keys:
        if name not in t.column_names:
            return False
        col = t[name].combine_chunks()
        a, b = col.slice(0, n - 1), col.slice(1)
        lt = np.asarray(pc.fill_null(pc.less(a, b), False))
        eq = np.asarray(pc.fill_null(pc.equal(a, b), False))
        bn = np.asarray(pc.and_(pc.is_null(a), pc.is_null(b)))
        an = np.asarray(pc.and_(pc.is_null(a), pc.invert(pc.is_null(b))))
        eq = eq | bn
        # nulls sort last: a null before a non-null is DESCENDING
        ok &= ~undecided | lt | eq
        ok &= ~(undecided & an)
        undecided &= eq
        if not ok.all():
            return False
    return bool(ok.all())


def make_memtable(schema: Schema, time_partition_ms: int, kind: str = "time_partition") -> Memtable:
    """Memtable builder selection (reference MemtableBuilderProvider,
    mito2/src/memtable/builder.rs): time_partition (default) |
    time_series (per-series vectors) | partition_tree (pk-sharded) |
    bulk (immutable bulk parts)."""
    if kind == "time_series":
        return TimeSeriesMemtable(schema, time_partition_ms)
    if kind == "partition_tree":
        return PartitionTreeMemtable(schema, time_partition_ms)
    if kind == "bulk":
        return BulkMemtable(schema, time_partition_ms)
    return Memtable(schema, time_partition_ms)
