"""TimeSeriesEngine: the region engine facade.

Role-equivalent of the reference's `MitoEngine` (reference
src/mito2/src/engine.rs:255) implementing the `RegionEngine` surface
(store-api/src/region_engine.rs:785): create/open/close/drop regions, route
write/flush/truncate/alter requests, serve scans, report region statistics.
Flush pressure is driven by a `WriteBufferManager` exactly like the
reference's (flush.rs): per-region and global thresholds, with stall
signalling when the global budget is exhausted.
"""

from __future__ import annotations

import os
import shutil
import threading

import pyarrow as pa

from ..datatypes.schema import Schema
from ..utils import metrics
from ..utils.config import StorageConfig
from ..utils.errors import RegionNotFoundError
from .flush import WriteBufferManager
from .region import Region, RegionStat
from .sst import ScanPredicate
from .wal import WalManager


class TimeSeriesEngine:
    def __init__(self, config: StorageConfig | None = None):
        from .object_store import build_object_store

        self.config = config or StorageConfig()
        os.makedirs(self.config.data_home, exist_ok=True)
        # SSTs + manifests live behind the object-store abstraction
        # (fs by default); the WAL is a local append log (raft-engine
        # analogue) or a shared-topic remote WAL for failover deployments.
        self.object_store = build_object_store(self.config)
        provider = getattr(self.config, "wal_provider", "local")
        if provider == "local":
            self.wal_mgr = WalManager(self.config.effective_wal_dir(), fsync=self.config.wal_fsync)
        elif provider == "shared_file":
            from .remote_wal import RemoteWalManager

            self.wal_mgr = RemoteWalManager(
                self.config.effective_wal_dir(),
                fsync=self.config.wal_fsync,
                num_topics=getattr(self.config, "wal_num_topics", 4),
                segment_bytes=getattr(self.config, "wal_segment_mb", 4) << 20,
            )
        elif provider == "kafka":
            endpoints = getattr(self.config, "wal_kafka_endpoints", "")
            if not endpoints:
                from ..utils.errors import ConfigError

                raise ConfigError(
                    "wal provider 'kafka' needs remote.kafka_endpoints (a "
                    "broker address — remote/fake_kafka.py runs one offline); "
                    "use 'shared_file' on shared storage for the same "
                    "failover semantics without a broker"
                )
            from ..remote.kafka import KafkaWalManager

            self.wal_mgr = KafkaWalManager(
                endpoints,
                num_topics=getattr(self.config, "wal_num_topics", 4),
                pool_size=getattr(self.config, "remote_pool_size", 2),
                call_deadline_s=getattr(self.config, "remote_call_deadline_s", 5.0),
                connect_timeout_s=getattr(self.config, "remote_connect_timeout_s", 2.0),
                retry_attempts=getattr(self.config, "remote_retry_attempts", 5),
            )
        else:
            from ..utils.errors import ConfigError

            raise ConfigError(f"unknown wal provider {provider!r}")
        self.buffer_mgr = WriteBufferManager(
            global_limit_bytes=self.config.global_write_buffer_size_mb << 20,
            region_limit_bytes=self.config.write_buffer_size_mb << 20,
        )
        self._regions: dict[int, Region] = {}
        self._lock = threading.Lock()
        # flush listeners: called with the region id after a flush that
        # added SSTs (the tile.prewarm_on_flush hook rides this); always
        # best-effort, never on the write path's critical section
        self.flush_listeners: list = []
        # delta listeners: called with (region_id, added_file_ids) — the
        # flush's delta notification, so tile maintenance can size its
        # incremental work.  A SEPARATE list (not arity-sniffed off
        # flush_listeners): signature guessing misdispatches callbacks
        # with defaulted or **kw second parameters
        self.delta_listeners: list = []
        self.compactor = None
        self.flusher = None
        self._workers = None  # lazy sharded write loops (storage/worker.py)
        if getattr(self.config, "async_flush_enable", True):
            from .maintenance import FlushScheduler

            self.flusher = FlushScheduler(self)
        if getattr(self.config, "compaction_background_enable", True):
            from .maintenance import CompactionScheduler

            self.compactor = CompactionScheduler(
                self,
                tick_secs=getattr(self.config, "compaction_tick_secs", 5.0),
                window_ms=(self.config.compaction_time_window_secs * 1000) or None,
                max_active_runs=self.config.compaction_max_active_window_runs,
                max_inactive_runs=self.config.compaction_max_inactive_window_runs,
                memory_mb=getattr(self.config, "compaction_memory_mb", 512),
            )
        # Follower freshness loop (replica.sync_interval_ms, copied down to
        # storage.follower_sync_interval_ms): read-only regions tail the
        # shared WAL + refresh their manifest view on this cadence.  0 (the
        # default) starts no thread and keeps open-time-snapshot followers.
        self.follower_syncer = None
        interval_ms = getattr(self.config, "follower_sync_interval_ms", 0.0)
        if interval_ms and interval_ms > 0:
            from .maintenance import FollowerSyncer

            self.follower_syncer = FollowerSyncer(self, interval_ms)

    # ---- region lifecycle -------------------------------------------------
    def create_region(
        self, region_id: int, schema: Schema, writable: bool = True,
        append_mode: bool = False, memtable_kind: str | None = None,
        merge_mode: str | None = None,
    ) -> Region:
        with self._lock:
            if region_id in self._regions:
                return self._regions[region_id]
            region = Region(
                region_id,
                self._region_store(region_id),
                schema,
                self.wal_mgr.region_wal(region_id),
                time_partition_ms=self.config.memtable_time_partition_secs * 1000,
                checkpoint_distance=self.config.manifest_checkpoint_distance,
                writable=writable,
                index_enable=self.config.index_enable,
                index_segment_rows=self.config.index_segment_rows,
                index_inverted_max_terms=self.config.index_inverted_max_terms,
                index_segmented=getattr(self.config, "index_segmented", True),
                index_segment_terms=getattr(self.config, "index_segment_terms", 512),
                index_max_terms=getattr(self.config, "index_max_terms", 1 << 20),
                append_mode=append_mode,
                merge_mode=merge_mode,
                memtable_kind=memtable_kind
                or getattr(self.config, "memtable_kind", "time_partition"),
                flush_workers=getattr(self.config, "ingest_flush_workers", 2),
            )
            self._wire_ingest(region)
            self._regions[region_id] = region
            return region

    def open_region(
        self, region_id: int, append_mode: bool = False, memtable_kind: str | None = None,
        merge_mode: str | None = None,
    ) -> Region:
        """Open an existing region from its manifest + WAL (crash recovery)."""
        with self._lock:
            if region_id in self._regions:
                return self._regions[region_id]
            store = self._region_store(region_id)
            if not store.list("manifest"):
                raise RegionNotFoundError(f"region {region_id} has no manifest")
            region = Region(
                region_id,
                store,
                Schema(columns=[]),  # overwritten by manifest recovery
                self.wal_mgr.region_wal(region_id),
                time_partition_ms=self.config.memtable_time_partition_secs * 1000,
                checkpoint_distance=self.config.manifest_checkpoint_distance,
                index_enable=self.config.index_enable,
                index_segment_rows=self.config.index_segment_rows,
                index_inverted_max_terms=self.config.index_inverted_max_terms,
                index_segmented=getattr(self.config, "index_segmented", True),
                index_segment_terms=getattr(self.config, "index_segment_terms", 512),
                index_max_terms=getattr(self.config, "index_max_terms", 1 << 20),
                append_mode=append_mode,
                merge_mode=merge_mode,
                memtable_kind=memtable_kind
                or getattr(self.config, "memtable_kind", "time_partition"),
                flush_workers=getattr(self.config, "ingest_flush_workers", 2),
            )
            self._wire_ingest(region)
            self._regions[region_id] = region
            return region

    def _wire_ingest(self, region: Region):
        """Flush-overlapped ingest (ingest.flush_overlap): give the region
        the write-buffer manager so freezing a memtable moves its bytes
        out of the mutable budget for the duration of the encode.  Off =
        no hook = pre-overlap stall accounting bit-for-bit."""
        if getattr(self.config, "ingest_flush_overlap", True):
            region.buffer_mgr = self.buffer_mgr

    def close_region(self, region_id: int):
        with self._lock:
            region = self._regions.pop(region_id, None)
        if region is not None and not region.writable:
            # a closing follower must stop pinning the shared-WAL tail
            region.release_follower_watermark()
        self.buffer_mgr.remove_region(region_id)

    def drop_region(self, region_id: int):
        self.close_region(region_id)
        self.wal_mgr.drop_region(region_id)
        store = self._region_store(region_id)
        for sub in ("manifest", "sst"):
            view = store.scoped(sub)
            for name in view.list():
                view.delete(name)
        shutil.rmtree(self._region_dir(region_id), ignore_errors=True)

    def region(self, region_id: int) -> Region:
        region = self._regions.get(region_id)
        if region is None:
            raise RegionNotFoundError(f"region {region_id} not found")
        return region

    def region_ids(self) -> list[int]:
        with self._lock:
            return list(self._regions)

    # ---- request routing --------------------------------------------------
    def write(self, region_id: int, batch: pa.RecordBatch) -> int:
        region = self.region(region_id)
        if self.buffer_mgr.should_stall():
            # Under pressure: flush the biggest offenders synchronously
            # instead of rejecting (single-process analogue of stalling).
            metrics.WRITE_STALL_TOTAL.inc()
            for rid in self.buffer_mgr.pick_flush_candidates():
                self.flush_region(rid)
                if not self.buffer_mgr.should_stall():
                    break
        rows = region.write(batch)
        self._post_write(region_id, region)
        return rows

    def write_group(self, region_id: int, batches: list[pa.RecordBatch]) -> list[int]:
        """Group-commit write (ingest.group_commit): one WAL frame for the
        whole group, per-write entry ids and row counts.  Same stall /
        flush-pressure envelope as `write`."""
        region = self.region(region_id)
        if self.buffer_mgr.should_stall():
            metrics.WRITE_STALL_TOTAL.inc()
            for rid in self.buffer_mgr.pick_flush_candidates():
                self.flush_region(rid)
                if not self.buffer_mgr.should_stall():
                    break
        rows = region.write_group(batches)
        self._post_write(region_id, region)
        return rows

    def _post_write(self, region_id: int, region: Region):
        self.buffer_mgr.set_region_usage(region_id, region.memtable.memory_usage)
        if self.buffer_mgr.should_flush_region(region_id) or self.buffer_mgr.should_flush_engine():
            # threshold flush runs OFF the write path (reference
            # FlushScheduler); stall flushes above stay synchronous
            if self.flusher is not None:
                self.flusher.schedule(region_id)
            else:
                self.flush_region(region_id)

    def delete(self, region_id: int, keys: pa.Table) -> int:
        """Tombstone-delete rows by (primary key, time index) keys.
        Tombstones are memtable writes too, so the same stall/flush
        backpressure as `write` applies."""
        region = self.region(region_id)
        if self.buffer_mgr.should_stall():
            metrics.WRITE_STALL_TOTAL.inc()
            for rid in self.buffer_mgr.pick_flush_candidates():
                self.flush_region(rid)
                if not self.buffer_mgr.should_stall():
                    break
        deleted = region.delete(keys)
        self.buffer_mgr.set_region_usage(region_id, region.memtable.memory_usage)
        if self.buffer_mgr.should_flush_region(region_id) or self.buffer_mgr.should_flush_engine():
            if self.flusher is not None:
                self.flusher.schedule(region_id)
            else:
                self.flush_region(region_id)
        return deleted

    def truncate_region(self, region_id: int):
        self.region(region_id).truncate()
        self.buffer_mgr.set_region_usage(region_id, 0)

    def flush_region(self, region_id: int):
        region = self._regions.get(region_id)
        if region is None:
            return
        added = region.flush()
        self.buffer_mgr.set_region_usage(region_id, region.memtable.memory_usage)
        if added and self.compactor is not None:
            self.compactor.notify_flush(region_id)
        if added:
            # delta notification: listeners learn WHICH SSTs the flush
            # appended, so tile maintenance can size its delta work (the
            # incremental super-tile build merges exactly these files'
            # rows instead of rebuilding from scratch)
            ids = [m.file_id for m in added]
            metrics.TILE_FLUSH_DELTA_FILES.inc(len(ids))
            for cb in list(self.flush_listeners):
                try:
                    cb(region_id)
                except Exception:  # noqa: BLE001 — listeners are advisory
                    pass
            for cb in list(self.delta_listeners):
                try:
                    cb(region_id, ids)
                except Exception:  # noqa: BLE001 — listeners are advisory
                    pass

    def flush_all(self):
        if self.flusher is not None:
            self.flusher.wait_idle()
        for rid in self.region_ids():
            self.flush_region(rid)

    def scan(
        self,
        region_id: int,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
    ) -> pa.Table:
        return self.region(region_id).scan(pred, columns)

    def region_statistics(self) -> list[RegionStat]:
        return [r.stat() for r in list(self._regions.values())]

    # ---- follower freshness -----------------------------------------------
    def sync_followers(self) -> dict[int, int]:
        """One WAL-tail + manifest-refresh round over every READ-ONLY
        region this engine hosts; returns {region_id: entries_applied}.
        Failures are per-region and transient by contract (shared-storage
        weather, a segment pruned mid-replay): the round records them and
        the next round resumes from the persisted applied position."""
        import logging

        out: dict[int, int] = {}
        for region in list(self._regions.values()):
            if region.writable:
                continue
            try:
                applied, _refreshed = region.follower_sync()
            except Exception as exc:  # noqa: BLE001 — next round retries
                metrics.FOLLOWER_SYNC_FAILURES_TOTAL.inc()
                logging.getLogger("greptimedb_tpu.engine").warning(
                    "follower sync of region %s failed: %s",
                    region.region_id, exc,
                )
                continue
            out[region.region_id] = applied
        return out

    # ---- helpers ----------------------------------------------------------
    def _region_dir(self, region_id: int) -> str:
        return os.path.join(self.config.effective_sst_dir(), f"region_{region_id}")

    def _region_store(self, region_id: int):
        return self.object_store.scoped(f"region_{region_id}")

    @property
    def workers(self):
        """Sharded single-writer-per-region loops with request batching
        (reference mito2/src/worker.rs WorkerGroup); created on first use
        so simple embedded engines never spawn threads."""
        if self._workers is None:
            from .worker import WorkerGroup

            with self._lock:
                if self._workers is None:
                    self._workers = WorkerGroup(
                        self, num_workers=self.config.num_workers
                    )
        return self._workers

    def submit_write(self, region_id: int, batch: pa.RecordBatch):
        """Queue a write on the region's worker loop; returns a Future of
        affected rows (pipelined ingest: protocol servers overlap decode
        of the next request with this write's WAL+memtable apply)."""
        return self.workers.submit_write(region_id, batch)

    def pending_writes(self, region_id: int) -> bool:
        """True when the region's worker loop has queued requests — i.e.
        a submitted write would coalesce into a drain group (WAL group
        commit) rather than run solo.  Never spawns the worker threads:
        no workers yet means nothing is pending."""
        if self._workers is None:
            return False
        return not self._workers._worker_for(region_id).queue.empty()

    def scan_stream(
        self,
        region_id: int,
        pred: ScanPredicate | None = None,
        columns: list[str] | None = None,
        governor=None,
    ):
        """Bounded-memory streaming scan: k-way merge over per-source
        sorted streams (Region.scan_merge_stream — one row group per source
        in memory), with the scan governor admitting each emitted batch."""
        for chunk in self.region(region_id).scan_merge_stream(pred, columns):
            if governor is not None:
                with governor.scan_guard(chunk.nbytes):
                    yield chunk
            else:
                yield chunk

    def close(self):
        if self.follower_syncer is not None:
            self.follower_syncer.stop()
        if self._workers is not None:
            self._workers.stop()
        if self.flusher is not None:
            self.flusher.stop()
        if self.compactor is not None:
            self.compactor.stop()
        for rid in self.region_ids():
            region = self._regions.get(rid)
            if region is not None and not region.writable:
                region.release_follower_watermark()
        self.wal_mgr.close()
