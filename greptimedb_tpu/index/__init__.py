"""Log-scale secondary index subsystem.

The original per-SST term indexes (`storage/index.py` InvertedIndex /
FulltextIndex) are whole-blob loads: one dict+bitmap payload per column,
deserialized in full on first touch.  Fine at dashboard cardinalities,
O(index) memory per query at log-tenant ones — a column with 10^7 unique
terms pays tens of MB of decode to answer one term lookup.

This package is the scalable replacement (reference: the `index` crate's
FST-backed inverted index with ranged puffin reads):

* `segmented` — the on-disk format and its builder/reader: a sorted term
  dictionary split into fixed-size segments, each written as its OWN
  puffin blob with delta-varint posting lists, plus one small meta blob
  holding the sparse fence-key array.  A term lookup is binary search
  over the in-memory fence keys -> ONE ranged `PuffinReader` read of one
  segment -> posting decode: O(log terms) time, O(segment) memory.
* `reader` — `TermIndexReader`, the shared per-SST router consulted by
  scan-time pruning: it serves segmented blobs and the legacy whole-blob
  formats through one interface (old SSTs keep working), degrades any
  segment-read failure to "cannot prune" (never a wrong result), and
  answers distinct-term stats the query planner's `agg_strategy` pass
  feeds on.
"""

from .reader import TermIndexReader
from .segmented import (
    TERM_META_BLOB,
    TERM_SEGMENT_BLOB,
    SegmentedTermIndex,
    build_term_postings,
    build_token_postings,
    write_term_index,
)

__all__ = [
    "TERM_META_BLOB",
    "TERM_SEGMENT_BLOB",
    "SegmentedTermIndex",
    "TermIndexReader",
    "build_term_postings",
    "build_token_postings",
    "write_term_index",
]
