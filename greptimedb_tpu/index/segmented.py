"""Segmented term index: fence-keyed term segments with ranged reads.

Layout (per indexed column, inside the SST's puffin sidecar):

    greptime-term-index-meta-v1   {column, kind}
        JSON: segment geometry + the sparse FENCE-KEY array (first term
        of every segment) + per-segment term counts.  Small: one fence
        per `seg_terms` terms (10^6 terms @ 512/segment = ~2000 fences).

    greptime-term-seg-v1          {column, kind, seg}  x n_segments
        Binary: the segment's sorted term dictionary (len-prefixed
        bytes) followed by one delta-encoded varint posting list per
        term.  Postings are ROW-SEGMENT ids (the same `segment_rows`
        granularity the bloom/legacy indexes prune at), so a decoded
        posting list expands to the row-segment candidacy bitmap the
        scan-time applier already consumes.

A term lookup binary-searches the fence keys (in memory after one small
meta read), issues ONE ranged puffin read for the single term segment
that can contain the term, and decodes O(seg_terms) entries — O(log
terms) time and O(segment) memory regardless of index size.  Decoded
segments live in a process-wide LRU so repeated lookups (dashboards
re-filtering the same tag) skip the read entirely.

Terms are stored as their canonical `storage.index._encode_value` bytes
(NULL sorts first via its \\x00 sentinel), so build-time and search-time
normalization agree with the legacy formats byte-for-byte.
"""

from __future__ import annotations

import bisect
import json
import struct
import threading
import time
from collections import OrderedDict

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..utils import metrics
from ..utils.fault_injection import fire as _fault_fire

TERM_META_BLOB = "greptime-term-index-meta-v1"
TERM_SEGMENT_BLOB = "greptime-term-seg-v1"

# terms longer than this are truncated at build AND lookup: collisions
# only widen the candidate bitmap (the residual filter stays exact)
MAX_TERM_BYTES = 1024

INDEX_LOOKUP_MS = metrics.REGISTRY.histogram(
    "greptime_index_lookup_ms",
    "Milliseconds per term-index lookup (fence search + segment read + decode)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 500.0),
)
INDEX_SEGMENTS_READ = metrics.REGISTRY.counter(
    "greptime_index_segments_read_total",
    "Term-index segment blobs fetched from storage (LRU misses)",
)
INDEX_BYTES_READ = metrics.REGISTRY.counter(
    "greptime_index_bytes_read_total",
    "Bytes fetched from term-index sidecars via ranged reads",
)
INDEX_SEGMENT_CACHE_HITS = metrics.REGISTRY.counter(
    "greptime_index_segment_cache_hits_total",
    "Term-index segment lookups served from the decoded-segment LRU",
)
INDEX_DEGRADED = metrics.REGISTRY.counter(
    "greptime_index_degraded_total",
    "Index lookups that degraded to a full scan after a read error",
)


# ---- varint codec -----------------------------------------------------------


def _write_varint(out: bytearray, v: int):
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    v = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


# ---- build ------------------------------------------------------------------


def build_term_postings(
    column: pa.Array, segment_rows: int
) -> tuple[list[bytes], list[np.ndarray], int]:
    """Tag column -> (sorted term bytes, per-term row-segment id arrays,
    n_row_segments).  Vectorized via dictionary encoding, like the legacy
    inverted builder, but with NO cardinality cap — segmenting is what
    makes high cardinality affordable."""
    from ..storage.index import _encode_value

    n = len(column)
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = pc.cast(column, column.type.value_type)
    n_segs = (n + segment_rows - 1) // segment_rows
    d = pc.dictionary_encode(column)
    dict_vals = d.dictionary.to_pylist()
    codes = np.asarray(
        pc.fill_null(pc.cast(d.indices, pa.int64()), len(dict_vals)), dtype=np.int64
    )
    seg_ids = np.arange(n, dtype=np.int64) // segment_rows
    # unique (code, row-seg) pairs; nulls ride code == len(dict_vals)
    pair = codes * n_segs + seg_ids
    pair = np.unique(pair)
    pcodes = pair // n_segs
    psegs = (pair % n_segs).astype(np.int64)
    keys = [_encode_value(v)[:MAX_TERM_BYTES] for v in dict_vals]
    if (codes == len(dict_vals)).any():
        keys.append(_encode_value(None))
    # group by term bytes (several dict values can normalize to one key);
    # pair is sorted, so pcodes is sorted — run boundaries, not per-code
    # masks (a mask per code is O(terms * pairs))
    by_key: dict[bytes, list[np.ndarray]] = {}
    uniq, starts = np.unique(pcodes, return_index=True)
    ends = np.append(starts[1:], len(pcodes))
    for code, s, e in zip(uniq, starts, ends):
        by_key.setdefault(keys[int(code)], []).append(psegs[s:e])
    terms = sorted(by_key)
    postings = [
        np.unique(np.concatenate(by_key[t])) if len(by_key[t]) > 1 else by_key[t][0]
        for t in terms
    ]
    return terms, postings, n_segs


def build_token_postings(
    column: pa.Array, segment_rows: int
) -> tuple[list[bytes], list[np.ndarray], int]:
    """Tokenized text column -> sorted token postings (fulltext kind)."""
    from ..storage.index import tokenize

    n = len(column)
    if isinstance(column, pa.ChunkedArray):
        column = column.combine_chunks()
    if pa.types.is_dictionary(column.type):
        column = pc.cast(column, column.type.value_type)
    n_segs = (n + segment_rows - 1) // segment_rows
    vocab: dict[str, set] = {}
    for i, v in enumerate(column.to_pylist()):
        if v is None:
            continue
        seg = i // segment_rows
        for t in tokenize(str(v)):
            vocab.setdefault(t, set()).add(seg)
    tok_by_bytes: dict[bytes, set] = {}
    for t, segs in vocab.items():
        tok_by_bytes.setdefault(t.encode()[:MAX_TERM_BYTES], set()).update(segs)
    terms = sorted(tok_by_bytes)
    postings = [np.array(sorted(tok_by_bytes[t]), dtype=np.int64) for t in terms]
    return terms, postings, n_segs


def _encode_segment(terms: list[bytes], postings: list[np.ndarray]) -> bytes:
    out = bytearray()
    out += struct.pack("<I", len(terms))
    for t in terms:
        out += struct.pack("<H", len(t))
        out += t
    for p in postings:
        _write_varint(out, len(p))
        prev = 0
        for v in p.tolist():
            _write_varint(out, v - prev)
            prev = v
    return bytes(out)


def _decode_segment(buf: bytes) -> dict[bytes, np.ndarray]:
    (n_terms,) = struct.unpack_from("<I", buf, 0)
    pos = 4
    terms: list[bytes] = []
    for _ in range(n_terms):
        (ln,) = struct.unpack_from("<H", buf, pos)
        pos += 2
        terms.append(buf[pos : pos + ln])
        pos += ln
    out: dict[bytes, np.ndarray] = {}
    for t in terms:
        cnt, pos = _read_varint(buf, pos)
        vals = np.empty(cnt, dtype=np.int64)
        prev = 0
        for i in range(cnt):
            d, pos = _read_varint(buf, pos)
            prev += d
            vals[i] = prev
        out[t] = vals
    return out


def write_term_index(
    writer,
    column: str,
    kind: str,
    terms: list[bytes],
    postings: list[np.ndarray],
    *,
    segment_rows: int,
    n_rows: int,
    n_segs: int,
    seg_terms: int = 512,
) -> int:
    """Emit the meta blob + one segment blob per `seg_terms` terms into
    `writer` (a PuffinWriter).  Returns the number of segment blobs."""
    fences: list[str] = []
    seg_lens: list[int] = []
    n_written = 0
    for start in range(0, len(terms), seg_terms):
        seg_t = terms[start : start + seg_terms]
        seg_p = postings[start : start + seg_terms]
        # latin-1 maps bytes 1:1 into JSON-safe codepoints, so the fence
        # round-trips EXACTLY even when MAX_TERM_BYTES truncation cut a
        # multibyte character in half — a utf-8 'replace' decode would
        # mangle such a fence and misroute every lookup near it (wrongly
        # pruning row groups that hold the term)
        fences.append(seg_t[0].decode("latin-1"))
        seg_lens.append(len(seg_t))
        writer.add_blob(
            TERM_SEGMENT_BLOB,
            _encode_segment(seg_t, seg_p),
            {"column": column, "kind": kind, "seg": n_written},
        )
        n_written += 1
    meta = {
        "version": 1,
        "kind": kind,
        "segment_rows": segment_rows,
        "n_rows": n_rows,
        "n_segs": n_segs,
        "n_terms": len(terms),
        "seg_terms": seg_terms,
        "fences": fences,
        "seg_lens": seg_lens,
    }
    writer.add_blob(
        TERM_META_BLOB, json.dumps(meta).encode(), {"column": column, "kind": kind}
    )
    return n_written


# ---- decoded-segment LRU ----------------------------------------------------


class SegmentCache:
    """Process-wide LRU of DECODED term segments, keyed by
    (sidecar identity, column, kind, segment id).  Entry-bounded: each
    entry is O(seg_terms) small objects, so a few hundred entries is a
    few MB — the working set of a dashboard's hot tags."""

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        self._data: OrderedDict[tuple, dict] = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key: tuple):
        with self._lock:
            v = self._data.get(key)
            if v is not None:
                self._data.move_to_end(key)
            return v

    def put(self, key: tuple, value: dict):
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)

    def clear(self):
        with self._lock:
            self._data.clear()


SEGMENT_CACHE = SegmentCache()


# ---- read -------------------------------------------------------------------


class SegmentedTermIndex:
    """One column's segmented term index, bound to a ranged PuffinReader.

    Holds the parsed meta (fence keys pre-encoded for bisect) and fetches
    term segments on demand through the shared LRU; every storage touch
    is a ranged read metered by greptime_index_{segments,bytes}_read."""

    def __init__(self, puffin, cache_key: str, column: str, kind: str, meta: dict):
        self._puffin = puffin
        self._cache_key = cache_key
        self.column = column
        self.kind = kind
        self.segment_rows = meta["segment_rows"]
        self.n_segs = meta["n_segs"]
        self.n_terms = meta["n_terms"]
        self._fences = [f.encode("latin-1") for f in meta["fences"]]
        self._seg_blobs: dict[int, object] | None = None  # seg id -> BlobMeta

    def _segment_blob(self, seg: int):
        if self._seg_blobs is None:
            self._seg_blobs = {
                m.properties.get("seg"): m
                for m in self._puffin.blobs()
                if m.blob_type == TERM_SEGMENT_BLOB
                and m.properties.get("column") == self.column
                and m.properties.get("kind") == self.kind
            }
        return self._seg_blobs.get(seg)

    def _segment(self, seg: int) -> dict[bytes, np.ndarray]:
        key = (self._cache_key, self.column, self.kind, seg)
        cached = SEGMENT_CACHE.get(key)
        if cached is not None:
            INDEX_SEGMENT_CACHE_HITS.inc()
            return cached
        _fault_fire("index.segment_read", column=self.column, seg=seg)
        bm = self._segment_blob(seg)
        if bm is None:
            raise FileNotFoundError(
                f"term segment {seg} of {self.column} missing from {self._puffin.key}"
            )
        before = self._puffin.bytes_read
        blob = self._puffin.read_blob(bm)
        INDEX_SEGMENTS_READ.inc()
        INDEX_BYTES_READ.inc(max(self._puffin.bytes_read - before, len(blob)))
        decoded = _decode_segment(blob)
        SEGMENT_CACHE.put(key, decoded)
        return decoded

    def lookup(self, term_bytes: bytes) -> np.ndarray:
        """Row-segment candidacy bitmap for ONE term.  Exact: an absent
        term returns all-False (the index is complete over the file)."""
        t0 = time.perf_counter()
        try:
            out = np.zeros(self.n_segs, dtype=bool)
            term_bytes = term_bytes[:MAX_TERM_BYTES]
            i = bisect.bisect_right(self._fences, term_bytes) - 1
            if i < 0:
                return out
            segs = self._segment(i).get(term_bytes)
            if segs is not None:
                out[segs] = True
            return out
        finally:
            INDEX_LOOKUP_MS.observe((time.perf_counter() - t0) * 1000.0)

    # -- predicate answering (mirrors the legacy classes' search API) --------

    def search(self, op: str, value) -> np.ndarray | None:
        if self.kind == "fulltext":
            return self._search_fulltext(op, value)
        return self._search_inverted(op, value)

    def _search_inverted(self, op: str, value) -> np.ndarray | None:
        from ..storage.index import _encode_value

        if op == "=":
            return self.lookup(_encode_value(value))
        if op == "in":
            out = np.zeros(self.n_segs, dtype=bool)
            for v in value:
                out |= self.lookup(_encode_value(v))
            return out
        # "!=" would have to union every OTHER term's postings — an
        # O(index) read that defeats the segmented contract; decline to
        # prune (the residual filter stays exact)
        return None

    def _search_fulltext(self, op: str, value) -> np.ndarray | None:
        from ..storage.index import parse_match_query, tokenize

        if op == "match_term":
            toks = tokenize(str(value))
            if not toks:
                return None
            out = np.ones(self.n_segs, dtype=bool)
            for t in toks:
                out &= self.lookup(t.encode())
            return out
        if op != "match":
            return None
        out = np.zeros(self.n_segs, dtype=bool)
        for terms, _phrases, _negs in parse_match_query(str(value)):
            # terms AND; phrases need substring scans over the whole
            # vocabulary (the legacy reader's _substr_token_segs), which a
            # ranged-read index cannot answer in O(segment) — skip the
            # phrase constraint (conservative: keeps more segments);
            # negations cannot prune either
            cand = np.ones(self.n_segs, dtype=bool)
            for t in terms:
                cand &= self.lookup(t.encode())
            out |= cand
        return out
