"""TermIndexReader: the shared per-SST index router.

Scan-time pruning (`storage/sst.py`) and the query planner's stats
probe both consult ONE object per SST sidecar instead of parsing blob
formats inline.  The router:

* serves the segmented term index (ranged reads, bounded memory) when
  the sidecar carries it, and falls back to the legacy whole-blob
  InvertedIndex / FulltextIndex / BloomIndex parses otherwise — SSTs
  written before `index.segmented` existed stay fully readable;
* degrades EVERY index failure (missing blob, torn segment, injected
  `index.segment_read` fault) to `None` = "cannot prune": the residual
  per-row filter still runs, so a broken index can cost a full scan but
  never a wrong result;
* answers `distinct_terms(column)` from the segmented meta blob — the
  table stats the `agg_strategy` planner pass sizes its hash table from,
  one small ranged read per (file, column).
"""

from __future__ import annotations

import json
import logging

import numpy as np

from ..storage import index as legacy
from ..storage.index import BLOOM_BLOB, FULLTEXT_BLOB, INVERTED_BLOB, VECTOR_BLOB
from ..storage.puffin import PuffinReader
from .segmented import (
    INDEX_BYTES_READ,
    INDEX_DEGRADED,
    TERM_META_BLOB,
    SegmentedTermIndex,
)

log = logging.getLogger("greptimedb_tpu.index")


class TermIndexReader:
    """Lazily-parsing router over one SST's puffin sidecar."""

    def __init__(self, store, file_id: str):
        self.file_id = file_id
        self._puffin = PuffinReader(store, f"{file_id}.puffin", ranged=True)
        self._cache_key = f"{getattr(store, 'root', id(store))}/{file_id}"
        self._metas = None  # blob list, or False when the sidecar is absent/broken
        self._parsed: dict[tuple, object] = {}  # (column, blob_type) -> parsed|None

    # -- sidecar inventory ----------------------------------------------------

    def _blobs(self):
        if self._metas is None:
            try:
                if not self._puffin.exists():
                    self._metas = False
                else:
                    self._metas = self._puffin.blobs()
            except Exception as e:  # noqa: BLE001 — degrade, never fail the scan
                log.warning("unreadable index sidecar %s: %s", self.file_id, e)
                INDEX_DEGRADED.inc()
                self._metas = False
        return self._metas or []

    def exists(self) -> bool:
        return bool(self._blobs())

    def _find(self, blob_type: str, column: str, **props):
        for m in self._blobs():
            if (
                m.blob_type == blob_type
                and m.properties.get("column") == column
                and all(m.properties.get(k) == v for k, v in props.items())
            ):
                return m
        return None

    def _get(self, column: str, blob_type: str, kind: str | None = None):
        """Parsed handle for (column, blob_type), cached; None = absent."""
        key = (column, blob_type, kind)
        if key in self._parsed:
            return self._parsed[key]
        out = None
        try:
            if blob_type == TERM_META_BLOB:
                bm = self._find(TERM_META_BLOB, column, kind=kind)
                if bm is not None:
                    before = self._puffin.bytes_read
                    meta = json.loads(self._puffin.read_blob(bm))
                    INDEX_BYTES_READ.inc(max(self._puffin.bytes_read - before, 0))
                    out = SegmentedTermIndex(
                        self._puffin, self._cache_key, column, kind, meta
                    )
            else:
                bm = self._find(blob_type, column)
                if bm is not None:
                    blob = self._puffin.read_blob(bm)
                    if blob_type == INVERTED_BLOB:
                        out = legacy.InvertedIndex(blob)
                    elif blob_type == FULLTEXT_BLOB:
                        out = legacy.FulltextIndex(blob)
                    elif blob_type == BLOOM_BLOB:
                        out = legacy.BloomIndex(blob)
                    elif blob_type == VECTOR_BLOB:
                        out = legacy.VectorIndex(blob)
        except Exception as e:  # noqa: BLE001 — degrade, never fail the scan
            log.warning(
                "index blob %s/%s of %s unreadable: %s",
                column, blob_type, self.file_id, e,
            )
            INDEX_DEGRADED.inc()
            out = None
        self._parsed[key] = out
        return out

    # -- the one search entry point ------------------------------------------

    def search(self, column: str, op: str, value) -> np.ndarray | None:
        """Row-segment candidacy bitmap for `column op value`, or None
        when this sidecar cannot (or must not, after an error) prune."""
        try:
            if op in ("match", "match_term"):
                seg = self._get(column, TERM_META_BLOB, "fulltext")
                if seg is not None:
                    bm = seg.search(op, value)
                    if bm is not None:
                        return bm
                ft = self._get(column, FULLTEXT_BLOB)
                return ft.search(op, value) if ft is not None else None
            seg = self._get(column, TERM_META_BLOB, "inverted")
            if seg is not None:
                bm = seg.search(op, value)
                if bm is not None:
                    return bm
            inv = self._get(column, INVERTED_BLOB)
            if inv is not None:
                bm = inv.search(op, value)
                if bm is not None:
                    return bm
            bloom = self._get(column, BLOOM_BLOB)
            return bloom.search(op, value) if bloom is not None else None
        except Exception as e:  # noqa: BLE001 — the full-scan-degrade contract
            log.warning(
                "index lookup %s %s on %s degraded to full scan: %s",
                column, op, self.file_id, e,
            )
            INDEX_DEGRADED.inc()
            return None

    def segment_rows(self) -> int:
        """Row-segment granularity of this sidecar's indexes."""
        for m in self._blobs():
            if m.blob_type == TERM_META_BLOB:
                h = self._get(
                    m.properties.get("column"), TERM_META_BLOB, m.properties.get("kind")
                )
                if h is not None:
                    return h.segment_rows
        for col, bt in [
            (m.properties.get("column"), m.blob_type)
            for m in self._blobs()
            if m.blob_type in (BLOOM_BLOB, INVERTED_BLOB, FULLTEXT_BLOB)
        ]:
            h = self._get(col, bt)
            if h is not None:
                return h.segment_rows
        return legacy.DEFAULT_SEGMENT_ROWS

    # -- auxiliary consumers --------------------------------------------------

    def vector_index(self, column: str):
        return self._get(column, VECTOR_BLOB)

    def distinct_terms(self, column: str) -> int | None:
        """Exact unique-term count of `column` IN THIS FILE, from the
        segmented meta blob (one small ranged read) — the cheap stats
        feed for the hash/sort aggregation planner.  None when this file
        has no segmented index for the column."""
        try:
            seg = self._get(column, TERM_META_BLOB, "inverted")
            return None if seg is None else int(seg.n_terms)
        except Exception:  # noqa: BLE001 — stats are advisory
            return None

    def has_segmented(self, column: str) -> bool:
        return self._find(TERM_META_BLOB, column, kind="inverted") is not None or (
            self._find(TERM_META_BLOB, column, kind="fulltext") is not None
        )
