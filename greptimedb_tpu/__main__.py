"""CLI: `python -m greptimedb_tpu <subcommand>`.

Role-equivalent of the reference's `greptime` binary (reference
cmd/src/bin/greptime.rs:26-61): `standalone start` brings up the all-in-one
server; `sql` executes statements against a data dir; `export`/`import`
move table data as Parquet (reference cli data export/import); `bench`
runs the TSBS-style benchmark.
"""

from __future__ import annotations

import argparse
import json
import sys


def cmd_standalone(args):
    from .database import Database
    from .servers.http import HttpServer
    from .servers.mysql import MysqlServer
    from .servers.postgres import PostgresServer
    from .utils.config import Config

    cfg = Config.load(args.config)
    if args.data_home:
        cfg.storage.data_home = args.data_home
        cfg.storage.wal_dir = ""
        cfg.storage.sst_dir = ""
        cfg.storage.__post_init__()
    if args.http_addr:
        cfg.server.http_addr = args.http_addr
    if args.mysql_addr:
        cfg.server.mysql_addr = args.mysql_addr
    if args.postgres_addr:
        cfg.server.postgres_addr = args.postgres_addr
    db = Database(config=cfg)
    srv = HttpServer(db, cfg.server.http_addr).start()
    mysql = MysqlServer(db, cfg.server.mysql_addr).start(warm=False)
    pg = PostgresServer(db, cfg.server.postgres_addr).start(warm=False)
    print(f"greptimedb-tpu standalone listening on http://{srv.address}", flush=True)
    print(f"mysql on {mysql.address}, postgres on {pg.address}", flush=True)
    print(f"data home: {cfg.storage.data_home}", flush=True)
    try:
        import signal
        import threading

        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        stop.wait()
    finally:
        pg.stop()
        mysql.stop()
        srv.stop()
        db.close()
    return 0


def cmd_sql(args):
    from .database import Database

    db = Database(data_home=args.data_home)
    try:
        text = args.query or sys.stdin.read()
        for result in db.sql(text):
            if result is None:
                print("OK")
            elif isinstance(result, int):
                print(f"{result} rows affected")
            else:
                print(result.to_pandas().to_string(index=False) if args.pretty else result)
    finally:
        db.close()
    return 0


def cmd_export(args):
    import pyarrow.parquet as pq

    from .database import Database
    from .query.logical_plan import TableScan

    db = Database(data_home=args.data_home)
    try:
        meta = db.catalog.table(args.table)
        table = db._scan(TableScan(args.table, meta.database))
        pq.write_table(table, args.output)
        print(f"exported {table.num_rows} rows to {args.output}")
    finally:
        db.close()
    return 0


def cmd_import(args):
    import pyarrow.parquet as pq

    from .database import Database

    db = Database(data_home=args.data_home)
    try:
        table = pq.read_table(args.input)
        n = db.insert_rows(args.table, table)
        print(f"imported {n} rows into {args.table}")
    finally:
        db.close()
    return 0


def cmd_datanode(args):
    """Run a standalone datanode process: a region server speaking Arrow
    Flight over shared storage (reference `greptime datanode start`).
    With --metasrv it registers its Flight address and heartbeats region
    stats (reference datanode/src/heartbeat.rs) so frontends discover it
    and the metasrv's failure detection has real input."""
    import signal
    import time as _time

    from .distributed.flight import DatanodeFlightServer
    from .storage.engine import TimeSeriesEngine
    from .utils.config import Config

    # layered config (env vars incl. GREPTIMEDB_TPU__REPLICA__SYNC_INTERVAL_MS,
    # which Config copies down to storage.follower_sync_interval_ms) with the
    # CLI data_home overriding whatever the layers said
    full_cfg = Config.load()
    storage_cfg = full_cfg.storage
    storage_cfg.data_home = args.data_home
    engine = TimeSeriesEngine(storage_cfg)
    # OTLP self-export: a bare datanode has no writer path for its own
    # spans (PR's trace table lives behind the SQL frontend), so when
    # trace.otlp_endpoint points at a frontend/standalone OTLP ingest,
    # ship the span ring there as protobuf batches instead
    otlp_task = None
    otlp_endpoint = getattr(full_cfg.trace, "otlp_endpoint", "")
    if otlp_endpoint:
        from .utils.self_trace import OtlpExportTask

        otlp_task = OtlpExportTask(
            otlp_endpoint, full_cfg.trace,
            service=f"greptimedb_tpu.datanode.{args.node_id}",
        ).start()
        print(f"otlp self-export -> {otlp_endpoint}", flush=True)
    host, port = (args.addr.rsplit(":", 1) + ["0"])[:2]
    server = DatanodeFlightServer(engine, f"grpc://{host}:{port}")
    import threading

    t = threading.Thread(target=server.serve, daemon=True)
    t.start()
    print(f"datanode {args.node_id} serving Flight at {server.location}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    meta = None
    if getattr(args, "metasrv", None):
        from .distributed.alive_keeper import RegionAliveKeeper
        from .distributed.meta_service import MetaClient

        meta = MetaClient(args.metasrv.split(","))
        flight_addr = server.location.removeprefix("grpc://")
        keeper = RegionAliveKeeper(args.node_id)

        def heartbeat_loop():
            import logging

            log = logging.getLogger("greptimedb_tpu.datanode")
            last_err = None
            while not stop.is_set():
                try:
                    now_ms = _time.time() * 1000
                    reply = meta.handle_heartbeat(
                        args.node_id,
                        [s.__dict__ for s in engine.region_statistics()],
                        now_ms,
                        addr=flight_addr,
                    )
                    keeper.renew(
                        reply.get("lease_regions", []),
                        reply.get("lease_until_ms", now_ms),
                    )
                    keeper.close_staled_regions(engine, now_ms)
                    last_err = None
                except Exception as e:  # noqa: BLE001 — metasrv may be electing
                    # log each DISTINCT failure once (a misconfiguration
                    # like a node-id/role conflict would otherwise spin
                    # silently forever at the heartbeat interval)
                    if str(e) != last_err:
                        last_err = str(e)
                        log.warning("heartbeat to metasrv failed: %s", e)
                    # the lease sweep runs EVEN when the metasrv is
                    # unreachable — a partitioned node's leases lapse on
                    # its own clock and its regions must close before the
                    # failed-over holder's compaction races ours
                    try:
                        keeper.close_staled_regions(engine, _time.time() * 1000)
                    except Exception:  # noqa: BLE001
                        pass
                    stop.wait(args.heartbeat_s)
                    continue
                # the metasrv drained its mailbox when it replied: apply
                # each instruction independently so one failure cannot
                # discard the rest of the batch (they are never requeued)
                for instr in reply.get("instructions", []):
                    try:
                        _apply_datanode_instruction(engine, instr)
                    except Exception:  # noqa: BLE001
                        log.warning("instruction %s failed", instr, exc_info=True)
                stop.wait(args.heartbeat_s)

        threading.Thread(target=heartbeat_loop, daemon=True).start()
    try:
        stop.wait()
    finally:
        if otlp_task is not None:
            otlp_task.stop()
        server.shutdown()
        engine.close()
    return 0


def _apply_datanode_instruction(engine, instr: dict):
    """Mailbox instructions from metasrv heartbeat replies (reference
    Instruction enum, common/meta/src/instruction.rs)."""
    kind = instr.get("kind")
    if kind == "open_region":
        engine.open_region(instr["region_id"])
    elif kind == "close_region":
        engine.close_region(instr["region_id"])
    elif kind == "flush_region":
        engine.flush_region(instr["region_id"])


def cmd_frontend(args):
    """Run a distributed frontend process: SQL over HTTP (+ MySQL) planned
    against metasrv routes and fanned out to Flight datanodes (reference
    `greptime frontend start`, frontend/src/instance.rs:110)."""
    import signal
    import threading
    import time as _time

    from .distributed.frontend import Frontend
    from .servers.http import HttpServer
    from .servers.mysql import MysqlServer

    fe = Frontend(
        args.data_home, args.metasrv.split(","), node_id=args.node_id
    )
    http = HttpServer(fe, args.http_addr).start(warm=False)
    mysql = None
    if args.mysql_addr:
        mysql = MysqlServer(fe, args.mysql_addr).start(warm=False)
    print(
        f"frontend {args.node_id} serving HTTP at {http.address}"
        + (f", MySQL at {mysql.address}" if mysql else ""),
        flush=True,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    while not stop.is_set():
        fe.heartbeat()
        stop.wait(args.heartbeat_s)
    http.stop()
    if mysql:
        mysql.stop()
    fe.close()
    return 0


def cmd_flownode(args):
    """Run a flownode process: the flow engine with a Flight service for
    mirrored inserts + flow DDL, heartbeating to the metasrv (reference
    `greptime flownode start`, flow/src/server.rs)."""
    from .distributed.flownode import run_flownode

    return run_flownode(args.node_id, args.data_home, args.addr, args.metasrv)


def cmd_metasrv(args):
    """Run a metasrv process: routes/heartbeats/placement/migration over
    HTTP with lease-based election on the shared KV (reference
    `greptime metasrv start`).  Datanodes are reached through Flight using
    --datanode node_id=host:port mappings."""
    import signal
    import threading

    from .distributed.election import LeaseElection
    from .distributed.flight import FlightDatanodeClient
    from .distributed.kv import FileKvBackend
    from .distributed.meta_service import MetasrvServer
    from .distributed.metasrv import Metasrv

    peers = {}
    for spec in args.datanode or []:
        nid, addr = spec.split("=", 1)
        peers[int(nid)] = addr

    class RemoteNodeManager:
        """NodeManager over Flight clients (reference common/meta
        NodeManager backed by per-peer gRPC clients).  Addresses come
        from static --datanode mappings or, preferentially, from what
        nodes registered via heartbeat (node_address role-equivalent)."""

        metasrv = None  # wired after construction

        def _client(self, node_id: int) -> FlightDatanodeClient:
            addr = None
            if self.metasrv is not None:
                addr = self.metasrv.node_addresses().get(node_id)
            addr = addr or peers.get(node_id)
            if addr is None:
                raise ConnectionError(f"datanode {node_id} has no known address")
            return FlightDatanodeClient(node_id, f"grpc://{addr}")

        def open_region(self, node_id: int, rid: int):
            self._client(node_id).open_region(rid)

        def open_follower(self, node_id: int, rid: int):
            self._client(node_id).open_region(rid, writable=False)

        def close_region_quiet(self, node_id: int, rid: int):
            try:
                self._client(node_id).close_region(rid)
            except Exception:  # noqa: BLE001
                pass

        def flush_region(self, node_id: int, rid: int):
            self._client(node_id).flush_region(rid)

        def set_region_writable(self, node_id: int, rid: int, writable: bool):
            self._client(node_id).set_region_writable(rid, writable)

    if getattr(args, "etcd_endpoints", None):
        # wire-level deployment: cluster metadata AND leader election live
        # in etcd (lease + create-revision CAS) so multiple metasrv
        # processes coordinate without a shared filesystem
        from .remote.etcd import EtcdClient, EtcdElection, EtcdKvBackend

        kv = EtcdKvBackend(args.etcd_endpoints)
        election = EtcdElection(
            EtcdClient(args.etcd_endpoints), args.node_id
        )
    else:
        kv = FileKvBackend(args.kv_dir)
        election = LeaseElection(kv, args.node_id)
    node_manager = RemoteNodeManager()
    metasrv = Metasrv(kv, node_manager, election=election)
    node_manager.metasrv = metasrv
    for nid, addr in peers.items():
        metasrv.register_datanode(nid, addr)
    server = MetasrvServer(metasrv, args.addr).start()
    print(f"metasrv {args.node_id} serving at {server.address}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    # campaign + supervise loop (reference metasrv election/heartbeat loops)
    import time as _time

    while not stop.is_set():
        try:
            election.campaign()
            if metasrv.is_leader():
                metasrv.tick(_time.time() * 1000)
        except Exception:  # noqa: BLE001 — supervision must outlive one bad tick
            import logging as _logging

            _logging.getLogger("greptimedb_tpu.metasrv").warning(
                "supervisor tick failed; retrying", exc_info=True
            )
        stop.wait(1.0)
    server.stop()
    return 0


def cmd_metadata(args):
    """metadata snapshot/restore/info (reference cli/src/metadata/:
    `greptime cli metadata snapshot save|restore` + control info).  The
    snapshot captures the catalog (tables, views, partition rules) and the
    per-table dictionaries index — enough to rebuild metadata after a
    catalog-file loss; region data (SSTs/WAL/manifests) is storage-level
    and restored by region replay, as in the reference."""
    import json
    import os
    import shutil

    catalog_path = os.path.join(args.data_home, "catalog.json")
    if args.action == "snapshot":
        if not os.path.exists(catalog_path):
            print(f"no catalog at {catalog_path}")
            return 1
        os.makedirs(os.path.dirname(os.path.abspath(args.out)) or ".", exist_ok=True)
        shutil.copyfile(catalog_path, args.out)
        with open(catalog_path) as f:
            state = json.load(f)
        n_tables = sum(len(ts) for ts in state.get("databases", {}).values())
        n_views = sum(len(vs) for vs in state.get("views", {}).values())
        print(f"snapshot written to {args.out}: {n_tables} tables, {n_views} views")
        return 0
    if args.action == "restore":
        with open(args.snapshot) as f:
            state = json.load(f)  # validates JSON before overwriting anything
        os.makedirs(args.data_home, exist_ok=True)
        tmp = catalog_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, catalog_path)
        print(f"catalog restored from {args.snapshot}")
        return 0
    if args.action == "info":
        if not os.path.exists(catalog_path):
            print(f"no catalog at {catalog_path}")
            return 1
        with open(catalog_path) as f:
            state = json.load(f)
        for db_name, tables in sorted(state.get("databases", {}).items()):
            for name, meta in sorted(tables.items()):
                print(f"table {db_name}.{name} id={meta.get('table_id')}")
            for vname in sorted(state.get("views", {}).get(db_name, {})):
                print(f"view  {db_name}.{vname}")
        return 0
    return 1


def cmd_objbench(args):
    """Object-storage micro-benchmark (reference `greptime datanode
    objbench`, cmd/src/datanode/objbench.rs): timed write/read/list/delete
    rounds against the configured store."""
    import json
    import time

    from .storage.object_store import build_object_store
    from .utils.config import StorageConfig

    cfg = StorageConfig(data_home=args.data_home)
    cfg.store_type = args.store_type
    store = build_object_store(cfg)
    payload = b"\xab" * (args.size_kb << 10)
    n = args.num_objects
    t0 = time.perf_counter()
    for i in range(n):
        store.write(f"objbench/{i:06d}.bin", payload)
    t_write = time.perf_counter() - t0
    t0 = time.perf_counter()
    total = 0
    for i in range(n):
        total += len(store.read(f"objbench/{i:06d}.bin"))
    t_read = time.perf_counter() - t0
    t0 = time.perf_counter()
    listed = len(store.list("objbench"))
    t_list = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(n):
        store.delete(f"objbench/{i:06d}.bin")
    t_delete = time.perf_counter() - t0
    print(json.dumps({
        "store_type": args.store_type,
        "objects": n,
        "object_kb": args.size_kb,
        "write_mb_s": round(n * args.size_kb / 1024 / max(t_write, 1e-9), 1),
        "read_mb_s": round(total / (1 << 20) / max(t_read, 1e-9), 1),
        "list_ms": round(t_list * 1000, 2),
        "listed": listed,
        "delete_per_s": round(n / max(t_delete, 1e-9)),
    }))
    return 0


def cmd_bench(args):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(prog="greptimedb-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("standalone", help="start the all-in-one server")
    p.add_argument("action", choices=["start"])
    p.add_argument("--config", default=None, help="TOML config path")
    p.add_argument("--data-home", default=None)
    p.add_argument("--http-addr", default=None)
    p.add_argument("--mysql-addr", default=None)
    p.add_argument("--postgres-addr", default=None)
    p.set_defaults(fn=cmd_standalone)

    p = sub.add_parser("sql", help="execute SQL against a data dir")
    p.add_argument("query", nargs="?", default=None, help="SQL text (stdin if omitted)")
    p.add_argument("--data-home", default="./greptimedb_data")
    p.add_argument("--pretty", action="store_true")
    p.set_defaults(fn=cmd_sql)

    p = sub.add_parser("export", help="export a table to Parquet")
    p.add_argument("table")
    p.add_argument("output")
    p.add_argument("--data-home", default="./greptimedb_data")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("import", help="import Parquet into a table")
    p.add_argument("table")
    p.add_argument("input")
    p.add_argument("--data-home", default="./greptimedb_data")
    p.set_defaults(fn=cmd_import)

    p = sub.add_parser("datanode", help="start a datanode (Flight region server)")
    p.add_argument("action", choices=["start"])
    p.add_argument("--node-id", type=int, default=0)
    p.add_argument("--data-home", default="./greptimedb_data")
    p.add_argument("--addr", default="127.0.0.1:0")
    p.add_argument("--metasrv", default=None,
                   help="comma-separated metasrv addrs to register with + heartbeat")
    p.add_argument("--heartbeat-s", type=float, default=1.0)
    p.set_defaults(fn=cmd_datanode)

    p = sub.add_parser(
        "frontend",
        help="start a distributed frontend (HTTP/MySQL over Flight datanodes)",
    )
    p.add_argument("action", choices=["start"])
    p.add_argument("--node-id", type=int, default=100)
    p.add_argument("--data-home", required=True,
                   help="shared storage root (catalog lives here)")
    p.add_argument("--metasrv", required=True,
                   help="comma-separated metasrv addrs")
    p.add_argument("--http-addr", default="127.0.0.1:0")
    p.add_argument("--mysql-addr", default=None)
    p.add_argument("--heartbeat-s", type=float, default=1.0)
    p.set_defaults(fn=cmd_frontend)

    p = sub.add_parser("flownode", help="start a flownode (streaming/batching flows)")
    p.add_argument("start", choices=["start"])
    p.add_argument("--node-id", type=int, default=1)
    p.add_argument("--data-home", required=True)
    p.add_argument("--addr", default="127.0.0.1:0")
    p.add_argument("--metasrv", default=None, help="metasrv addr for heartbeats")
    p.set_defaults(fn=cmd_flownode)

    p = sub.add_parser("metasrv", help="start a metasrv (routes/heartbeats/election)")
    p.add_argument("action", choices=["start"])
    p.add_argument("--node-id", default="metasrv-0")
    p.add_argument("--kv-dir", default="./greptimedb_meta")
    p.add_argument("--addr", default="127.0.0.1:0")
    p.add_argument(
        "--datanode", action="append",
        help="node_id=host:port mapping (repeatable)",
    )
    p.add_argument(
        "--etcd-endpoints", default="",
        help="etcd v3 grpc-gateway endpoints (host:port[,host:port]); "
        "replaces --kv-dir with a wire-level KV + election backend",
    )
    p.set_defaults(fn=cmd_metasrv)

    p = sub.add_parser("metadata", help="catalog snapshot / restore / info")
    p.add_argument("action", choices=["snapshot", "restore", "info"])
    p.add_argument("--data-home", default="./greptimedb_data")
    p.add_argument("--out", default="./catalog_snapshot.json", help="snapshot output path")
    p.add_argument("--snapshot", default="./catalog_snapshot.json", help="snapshot to restore")
    p.set_defaults(fn=cmd_metadata)

    p = sub.add_parser("objbench", help="object-storage micro-benchmark")
    p.add_argument("--data-home", default="/tmp/greptimedb_objbench")
    p.add_argument("--store-type", default="fs", choices=["fs", "memory"])
    p.add_argument("--num-objects", type=int, default=64)
    p.add_argument("--size-kb", type=int, default=1024)
    p.set_defaults(fn=cmd_objbench)

    p = sub.add_parser("bench", help="run the TSBS-style benchmark")
    p.set_defaults(fn=cmd_bench)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
