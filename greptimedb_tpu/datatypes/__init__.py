from .data_type import ConcreteDataType
from .schema import ColumnSchema, Schema, SemanticType

__all__ = ["ConcreteDataType", "ColumnSchema", "Schema", "SemanticType"]
