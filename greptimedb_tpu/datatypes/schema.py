"""Schema / ColumnSchema with time-index and semantic-type metadata.

Mirrors the reference's `Schema`/`ColumnSchema` (reference
src/datatypes/src/schema/) and the TAG/FIELD/TIMESTAMP semantic split that
the metric engine and PromQL planner rely on (reference
src/store-api/src/metadata.rs `SemanticType`).
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field

import pyarrow as pa

from ..utils.errors import ColumnNotFoundError, InvalidArgumentsError
from .data_type import ConcreteDataType


class SemanticType(enum.IntEnum):
    TAG = 0        # primary-key member (series identity)
    FIELD = 1      # measured value
    TIMESTAMP = 2  # the single time index


@dataclass
class ColumnSchema:
    name: str
    data_type: ConcreteDataType
    semantic_type: SemanticType = SemanticType.FIELD
    nullable: bool = True
    default: object = None
    # Stable column identity (reference store-api ColumnMetadata.column_id):
    # survives renames of other columns and distinguishes a re-added column
    # from a previously dropped one of the same name.  0 = unassigned; the
    # Schema constructor allocates ids.
    column_id: int = 0
    # Fulltext-indexed (reference datatypes fulltext options on ColumnSchema;
    # declared as `col STRING FULLTEXT INDEX` — SSTs get a tokenized
    # inverted index consulted by matches()/matches_term()).
    fulltext: bool = False
    # VECTOR(dim) columns: embedding dimension (reference VectorType dim;
    # values are little-endian f32 bytes).  `VECTOR INDEX` adds an IVF-flat
    # ANN sidecar at flush.
    vector_dim: int | None = None
    vector_index: bool = False

    def __post_init__(self):
        if self.semantic_type == SemanticType.TIMESTAMP:
            if not self.data_type.is_timestamp():
                raise InvalidArgumentsError(
                    f"time index column {self.name!r} must be a timestamp, got {self.data_type}"
                )
            self.nullable = False

    def to_arrow(self) -> pa.Field:
        meta = {
            b"greptime:semantic_type": str(int(self.semantic_type)).encode(),
            b"greptime:type": self.data_type.value.encode(),
            b"greptime:column_id": str(self.column_id).encode(),
        }
        return pa.field(self.name, self.data_type.to_arrow(), nullable=self.nullable, metadata=meta)

    @classmethod
    def from_arrow(cls, f: pa.Field) -> "ColumnSchema":
        meta = f.metadata or {}
        sem = SemanticType(int(meta.get(b"greptime:semantic_type", b"1")))
        return cls(
            name=f.name,
            data_type=ConcreteDataType.from_arrow(f.type),
            semantic_type=sem,
            nullable=f.nullable,
            column_id=int(meta.get(b"greptime:column_id", 0)),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "data_type": self.data_type.value,
            "semantic_type": int(self.semantic_type),
            "nullable": self.nullable,
            "default": self.default,
            "column_id": self.column_id,
            "fulltext": self.fulltext,
            "vector_dim": self.vector_dim,
            "vector_index": self.vector_index,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ColumnSchema":
        return cls(
            name=d["name"],
            data_type=ConcreteDataType(d["data_type"]),
            semantic_type=SemanticType(d["semantic_type"]),
            nullable=d.get("nullable", True),
            default=d.get("default"),
            column_id=d.get("column_id", 0),
            fulltext=d.get("fulltext", False),
            vector_dim=d.get("vector_dim"),
            vector_index=d.get("vector_index", False),
        )


@dataclass
class Schema:
    columns: list[ColumnSchema] = field(default_factory=list)
    version: int = 0
    # Monotonic id allocator — never reused, even after DROP COLUMN, so a
    # re-added name gets a NEW id and old SST data for the dropped column
    # reads as NULL instead of resurrecting (reference mito2 compat by
    # column_id).  0 = derive from the columns present.
    next_column_id: int = 0

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise InvalidArgumentsError(f"duplicate column names in schema: {names}")
        ts = [c for c in self.columns if c.semantic_type == SemanticType.TIMESTAMP]
        if len(ts) > 1:
            raise InvalidArgumentsError("schema may have at most one time index column")
        # Allocate ids for unassigned columns (fresh CREATE or legacy data):
        # position-based, deterministic across identical schema builds.
        max_id = max((c.column_id for c in self.columns), default=0)
        for c in self.columns:
            if c.column_id == 0:
                max_id += 1
                c.column_id = max_id
        if self.next_column_id <= max_id:
            self.next_column_id = max_id + 1
        self._index = {c.name: i for i, c in enumerate(self.columns)}

    # ---- access -----------------------------------------------------------
    def column(self, name: str) -> ColumnSchema:
        i = self._index.get(name)
        if i is None:
            raise ColumnNotFoundError(f"column not found: {name}")
        return self.columns[i]

    def column_index(self, name: str) -> int:
        i = self._index.get(name)
        if i is None:
            raise ColumnNotFoundError(f"column not found: {name}")
        return i

    def has_column(self, name: str) -> bool:
        return name in self._index

    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    @property
    def time_index(self) -> ColumnSchema | None:
        for c in self.columns:
            if c.semantic_type == SemanticType.TIMESTAMP:
                return c
        return None

    def tag_columns(self) -> list[ColumnSchema]:
        return [c for c in self.columns if c.semantic_type == SemanticType.TAG]

    def field_columns(self) -> list[ColumnSchema]:
        return [c for c in self.columns if c.semantic_type == SemanticType.FIELD]

    def primary_key(self) -> list[str]:
        return [c.name for c in self.tag_columns()]

    # ---- evolution (reference mito2/src/read/compat.rs) -------------------
    def add_column(self, col: ColumnSchema) -> "Schema":
        if self.has_column(col.name):
            raise InvalidArgumentsError(f"column {col.name!r} already exists")
        import dataclasses

        col = dataclasses.replace(col, column_id=self.next_column_id)
        return Schema(
            columns=self.columns + [col],
            version=self.version + 1,
            next_column_id=self.next_column_id + 1,
        )

    def drop_column(self, name: str) -> "Schema":
        col = self.column(name)
        if col.semantic_type != SemanticType.FIELD:
            raise InvalidArgumentsError("only FIELD columns can be dropped")
        return Schema(
            columns=[c for c in self.columns if c.name != name],
            version=self.version + 1,
            next_column_id=self.next_column_id,
        )

    # ---- conversions ------------------------------------------------------
    def to_arrow(self) -> pa.Schema:
        return pa.schema(
            [c.to_arrow() for c in self.columns],
            metadata={b"greptime:version": str(self.version).encode()},
        )

    @classmethod
    def from_arrow(cls, s: pa.Schema) -> "Schema":
        version = int((s.metadata or {}).get(b"greptime:version", b"0"))
        return cls(columns=[ColumnSchema.from_arrow(f) for f in s], version=version)

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": self.version,
                "next_column_id": self.next_column_id,
                "columns": [c.to_dict() for c in self.columns],
            }
        )

    @classmethod
    def from_json(cls, s: str) -> "Schema":
        d = json.loads(s)
        return cls(
            columns=[ColumnSchema.from_dict(c) for c in d["columns"]],
            version=d["version"],
            next_column_id=d.get("next_column_id", 0),
        )
