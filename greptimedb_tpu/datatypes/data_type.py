"""ConcreteDataType: the logical type lattice over Arrow physical types.

Mirrors the reference's `ConcreteDataType` (reference
src/datatypes/src/data_type.rs) but maps directly onto pyarrow types; the
TPU path additionally defines the JAX dtype each type lowers to (strings and
other non-numeric types are dictionary-encoded to int32 codes on the host
before tiling, the same trick as the reference's primary-key pre-encoding in
mito-codec/src/row_converter/).
"""

from __future__ import annotations

import enum

import numpy as np
import pyarrow as pa

from ..utils.errors import InvalidArgumentsError


class ConcreteDataType(enum.Enum):
    NULL = "null"
    BOOLEAN = "boolean"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"
    BINARY = "binary"
    DATE = "date"
    TIMESTAMP_SECOND = "timestamp_s"
    TIMESTAMP_MILLISECOND = "timestamp_ms"
    TIMESTAMP_MICROSECOND = "timestamp_us"
    TIMESTAMP_NANOSECOND = "timestamp_ns"
    INTERVAL = "interval"
    JSON = "json"
    # Fixed-dimension float32 embedding, stored as little-endian f32 bytes
    # (reference datatypes vector type, stored as binary with dim metadata;
    # the dimension lives on ColumnSchema.vector_dim).
    VECTOR = "vector"

    # ---- classification ---------------------------------------------------
    def is_timestamp(self) -> bool:
        return self.value.startswith("timestamp")

    def is_numeric(self) -> bool:
        return self in _NUMERIC

    def is_float(self) -> bool:
        return self in (ConcreteDataType.FLOAT32, ConcreteDataType.FLOAT64)

    def is_signed(self) -> bool:
        return self in (
            ConcreteDataType.INT8,
            ConcreteDataType.INT16,
            ConcreteDataType.INT32,
            ConcreteDataType.INT64,
        )

    def is_string(self) -> bool:
        return self in (ConcreteDataType.STRING, ConcreteDataType.JSON)

    def timestamp_unit_ns(self) -> int:
        """Nanoseconds per unit of this timestamp type."""
        return {
            ConcreteDataType.TIMESTAMP_SECOND: 1_000_000_000,
            ConcreteDataType.TIMESTAMP_MILLISECOND: 1_000_000,
            ConcreteDataType.TIMESTAMP_MICROSECOND: 1_000,
            ConcreteDataType.TIMESTAMP_NANOSECOND: 1,
        }[self]

    # ---- conversions ------------------------------------------------------
    def to_arrow(self) -> pa.DataType:
        return _TO_ARROW[self]

    @classmethod
    def from_arrow(cls, t: pa.DataType) -> "ConcreteDataType":
        if pa.types.is_dictionary(t):
            return cls.from_arrow(t.value_type)
        for cdt, at in _TO_ARROW.items():
            if at == t:
                return cdt
        if pa.types.is_timestamp(t):
            return _TS_BY_UNIT[t.unit]
        if pa.types.is_large_string(t) or pa.types.is_string_view(t):
            return cls.STRING
        if pa.types.is_large_binary(t):
            return cls.BINARY
        raise InvalidArgumentsError(f"unsupported arrow type: {t}")

    @classmethod
    def parse(cls, s: str) -> "ConcreteDataType":
        """Parse a SQL type name (CREATE TABLE surface)."""
        key = s.strip().lower()
        if key in _SQL_ALIASES:
            return _SQL_ALIASES[key]
        if key.startswith("vector(") and key.endswith(")"):
            return cls.VECTOR
        raise InvalidArgumentsError(f"unknown data type: {s!r}")

    def to_numpy(self) -> np.dtype:
        if self.is_timestamp():
            return np.dtype("int64")
        if self == ConcreteDataType.BOOLEAN:
            return np.dtype("bool")
        if self in (
            ConcreteDataType.STRING,
            ConcreteDataType.BINARY,
            ConcreteDataType.JSON,
            ConcreteDataType.VECTOR,
        ):
            return np.dtype("object")
        return np.dtype(self.value)

    def to_jax(self):
        """The on-device dtype this column lowers to (None = host-encoded)."""
        import jax.numpy as jnp

        if self.is_timestamp() or self in (ConcreteDataType.INT64, ConcreteDataType.UINT64):
            return jnp.int64
        if self == ConcreteDataType.BOOLEAN:
            return jnp.bool_
        if self in (ConcreteDataType.FLOAT32,):
            return jnp.float32
        if self == ConcreteDataType.FLOAT64:
            return jnp.float64
        if self.is_numeric():
            return jnp.int32
        return None  # dictionary-encode on host -> int32 codes


_NUMERIC = {
    ConcreteDataType.INT8,
    ConcreteDataType.INT16,
    ConcreteDataType.INT32,
    ConcreteDataType.INT64,
    ConcreteDataType.UINT8,
    ConcreteDataType.UINT16,
    ConcreteDataType.UINT32,
    ConcreteDataType.UINT64,
    ConcreteDataType.FLOAT32,
    ConcreteDataType.FLOAT64,
}

_TO_ARROW = {
    ConcreteDataType.NULL: pa.null(),
    ConcreteDataType.BOOLEAN: pa.bool_(),
    ConcreteDataType.INT8: pa.int8(),
    ConcreteDataType.INT16: pa.int16(),
    ConcreteDataType.INT32: pa.int32(),
    ConcreteDataType.INT64: pa.int64(),
    ConcreteDataType.UINT8: pa.uint8(),
    ConcreteDataType.UINT16: pa.uint16(),
    ConcreteDataType.UINT32: pa.uint32(),
    ConcreteDataType.UINT64: pa.uint64(),
    ConcreteDataType.FLOAT32: pa.float32(),
    ConcreteDataType.FLOAT64: pa.float64(),
    ConcreteDataType.STRING: pa.string(),
    ConcreteDataType.BINARY: pa.binary(),
    ConcreteDataType.DATE: pa.date32(),
    ConcreteDataType.TIMESTAMP_SECOND: pa.timestamp("s"),
    ConcreteDataType.TIMESTAMP_MILLISECOND: pa.timestamp("ms"),
    ConcreteDataType.TIMESTAMP_MICROSECOND: pa.timestamp("us"),
    ConcreteDataType.TIMESTAMP_NANOSECOND: pa.timestamp("ns"),
    ConcreteDataType.INTERVAL: pa.duration("ms"),
    ConcreteDataType.JSON: pa.string(),
    ConcreteDataType.VECTOR: pa.binary(),
}

_TS_BY_UNIT = {
    "s": ConcreteDataType.TIMESTAMP_SECOND,
    "ms": ConcreteDataType.TIMESTAMP_MILLISECOND,
    "us": ConcreteDataType.TIMESTAMP_MICROSECOND,
    "ns": ConcreteDataType.TIMESTAMP_NANOSECOND,
}

_SQL_ALIASES = {
    "boolean": ConcreteDataType.BOOLEAN,
    "bool": ConcreteDataType.BOOLEAN,
    "tinyint": ConcreteDataType.INT8,
    "int8": ConcreteDataType.INT8,
    "smallint": ConcreteDataType.INT16,
    "int16": ConcreteDataType.INT16,
    "int": ConcreteDataType.INT32,
    "integer": ConcreteDataType.INT32,
    "int32": ConcreteDataType.INT32,
    "bigint": ConcreteDataType.INT64,
    "int64": ConcreteDataType.INT64,
    "tinyint unsigned": ConcreteDataType.UINT8,
    "uint8": ConcreteDataType.UINT8,
    "smallint unsigned": ConcreteDataType.UINT16,
    "uint16": ConcreteDataType.UINT16,
    "int unsigned": ConcreteDataType.UINT32,
    "uint32": ConcreteDataType.UINT32,
    "bigint unsigned": ConcreteDataType.UINT64,
    "uint64": ConcreteDataType.UINT64,
    "float": ConcreteDataType.FLOAT32,
    "float32": ConcreteDataType.FLOAT32,
    "real": ConcreteDataType.FLOAT32,
    "double": ConcreteDataType.FLOAT64,
    "float64": ConcreteDataType.FLOAT64,
    "string": ConcreteDataType.STRING,
    "text": ConcreteDataType.STRING,
    "varchar": ConcreteDataType.STRING,
    "char": ConcreteDataType.STRING,
    "binary": ConcreteDataType.BINARY,
    "varbinary": ConcreteDataType.BINARY,
    "blob": ConcreteDataType.BINARY,
    "date": ConcreteDataType.DATE,
    "timestamp": ConcreteDataType.TIMESTAMP_MILLISECOND,
    "timestamp_s": ConcreteDataType.TIMESTAMP_SECOND,
    "timestamp_ms": ConcreteDataType.TIMESTAMP_MILLISECOND,
    "timestamp_us": ConcreteDataType.TIMESTAMP_MICROSECOND,
    "timestamp_ns": ConcreteDataType.TIMESTAMP_NANOSECOND,
    "timestamp(0)": ConcreteDataType.TIMESTAMP_SECOND,
    "timestamp(3)": ConcreteDataType.TIMESTAMP_MILLISECOND,
    "timestamp(6)": ConcreteDataType.TIMESTAMP_MICROSECOND,
    "timestamp(9)": ConcreteDataType.TIMESTAMP_NANOSECOND,
    "json": ConcreteDataType.JSON,
    "interval": ConcreteDataType.INTERVAL,
}
