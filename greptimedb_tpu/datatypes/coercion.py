"""Literal/type coercion shared by the execution and scan-pruning paths.

Prepared-statement emulation substitutes every parameter as a quoted string
(reference servers/src/mysql/handler.rs does the same), so comparisons like
`v > '1'` against numeric columns must coerce the literal — the reference
gets this from DataFusion's type analyzer.  One helper, used by both
query/cpu_exec.py and storage/sst.py, so pruning and execution can never
disagree.
"""

from __future__ import annotations

import pyarrow as pa


def coerce_string_scalar(value, target: pa.DataType):
    """Cast a string (py str or pa string Scalar) to `target` if it is a
    numeric/bool type; returns the input unchanged when not applicable or
    unparseable (the comparison then fails with arrow's own error)."""
    is_scalar = isinstance(value, pa.Scalar)
    if is_scalar and not pa.types.is_string(value.type):
        return value
    if not is_scalar and not isinstance(value, str):
        return value
    if not (
        pa.types.is_integer(target)
        or pa.types.is_floating(target)
        or pa.types.is_boolean(target)
    ):
        return value
    try:
        return (value if is_scalar else pa.scalar(value)).cast(target)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        return value
