"""Pipeline: YAML-defined log ETL (the reference's `pipeline` crate).

Processors parse/reshape incoming log documents, transforms type them into
table rows, a dispatcher can fan documents out to other pipelines/tables
(reference src/pipeline/src/etl.rs, dispatcher.rs, manager/).
"""

from .etl import Pipeline, PipelineExecError, PipelineParseError, parse_pipeline
from .manager import (
    GREPTIME_IDENTITY,
    PipelineManager,
    run_pipeline_ingest,
)

__all__ = [
    "GREPTIME_IDENTITY",
    "Pipeline",
    "PipelineExecError",
    "PipelineManager",
    "PipelineParseError",
    "parse_pipeline",
    "run_pipeline_ingest",
]
