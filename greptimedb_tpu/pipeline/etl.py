"""Pipeline ETL core: YAML parse, processors, transforms, dispatcher.

Role-equivalent of the reference's etl module (reference
src/pipeline/src/etl.rs `Pipeline::exec_mut`, etl/processor/*.rs,
etl/transform/): documents (dicts) flow through an ordered processor list,
an optional dispatcher routes them to other pipelines/table suffixes, and a
transform section types the surviving fields into storage rows.
"""

from __future__ import annotations

import datetime
import json
import re
import urllib.parse
from dataclasses import dataclass, field

from ..datatypes.data_type import ConcreteDataType
from ..utils.errors import GreptimeError, StatusCode


class PipelineParseError(GreptimeError):
    def status_code(self) -> StatusCode:
        return StatusCode.INVALID_ARGUMENTS


class PipelineExecError(GreptimeError):
    def status_code(self) -> StatusCode:
        return StatusCode.INVALID_ARGUMENTS


class DropDocument(Exception):
    """Raised by the filter processor to discard the current document."""


class TsNs(int):
    """Epoch-nanosecond value produced by a date/epoch processor.

    Marks the value as already-normalized so a downstream timestamp
    transform rescales from ns, while a raw (unprocessed) number is
    interpreted in the transform's declared unit — matching the reference,
    where processors emit typed Timestamp values and `type: epoch, ms`
    on a raw field means "this number is in ms"."""

    __slots__ = ()


# ---- helpers ----------------------------------------------------------------


def _as_fields(cfg: dict, *, required: bool = True) -> list[str]:
    v = cfg.get("fields", cfg.get("field"))
    if v is None:
        if required:
            raise PipelineParseError("processor requires field/fields")
        return []
    if isinstance(v, str):
        # a single field spec, possibly a "src, dst" rename — NOT a list
        return [v]
    return [str(x) for x in v]


def _split_rename(f: str) -> tuple[str, str]:
    """`src, dst` field spec (reference etl/field.rs `Field`)."""
    if "," in f:
        a, b = f.split(",", 1)
        return a.strip(), b.strip()
    return f, f


# ---- processors -------------------------------------------------------------


class Processor:
    """One step of the ETL chain; mutates the document dict in place."""

    def __init__(self, cfg: dict):
        self.cfg = cfg or {}
        self.fields = [_split_rename(f) for f in _as_fields(self.cfg, required=self._needs_fields())]
        self.ignore_missing = bool(self.cfg.get("ignore_missing", False))

    def _needs_fields(self) -> bool:
        return True

    def __call__(self, doc: dict):
        for src, dst in self.fields:
            if src not in doc:
                if self.ignore_missing:
                    continue
                raise PipelineExecError(f"field {src!r} missing (processor {type(self).__name__})")
            self.apply(doc, src, dst)

    def apply(self, doc: dict, src: str, dst: str):
        raise NotImplementedError


class DissectProcessor(Processor):
    """Pattern tokenizer (reference etl/processor/dissect.rs): literal
    separators between %{name} captures; modifiers: %{?skip}, %{+append},
    %{name->} (greedy trailing separator)."""

    def __init__(self, cfg: dict):
        super().__init__(cfg)
        patterns = cfg.get("patterns") or ([cfg["pattern"]] if "pattern" in cfg else [])
        if not patterns:
            raise PipelineParseError("dissect requires patterns")
        self.append_separator = str(cfg.get("append_separator", " "))
        self.patterns = [self._compile(p) for p in patterns]

    _TOKEN = re.compile(r"%\{([^}]*)\}")

    def _compile(self, pattern: str):
        parts = []  # alternating literal, key-spec
        pos = 0
        for m in self._TOKEN.finditer(pattern):
            parts.append(("lit", pattern[pos : m.start()]))
            parts.append(("key", m.group(1)))
            pos = m.end()
        parts.append(("lit", pattern[pos:]))
        return parts

    def apply(self, doc: dict, src: str, dst: str):
        text = str(doc[src])
        for parts in self.patterns:
            out = self._try(parts, text)
            if out is not None:
                doc.update(out)
                return
        raise PipelineExecError(f"dissect: no pattern matched {text[:80]!r}")

    def _try(self, parts, text: str):
        out: dict = {}
        appends: dict[str, list[str]] = {}
        i = 0
        k = 0
        while k < len(parts):
            kind, spec = parts[k]
            if kind == "lit":
                if spec:
                    if not text.startswith(spec, i):
                        return None
                    i += len(spec)
                k += 1
                continue
            # key: find the next literal to bound the capture
            next_lit = ""
            for kk in range(k + 1, len(parts)):
                if parts[kk][0] == "lit" and parts[kk][1]:
                    next_lit = parts[kk][1]
                    break
            if next_lit:
                j = text.find(next_lit, i)
                if j < 0:
                    return None
            else:
                j = len(text)
            value = text[i:j]
            i = j
            name = spec
            greedy = name.endswith("->")
            if greedy:
                name = name[:-2]
            if greedy and next_lit:
                # %{name->}: swallow repeated separators, leaving one for the
                # following literal part to consume
                while text.startswith(next_lit * 2, i):
                    i += len(next_lit)
            if name.startswith("?") or name == "":
                pass  # named-skip
            elif name.startswith("+"):
                appends.setdefault(name[1:], []).append(value)
            else:
                out[name] = value
            k += 1
        for name, vals in appends.items():
            out[name] = self.append_separator.join(vals)
        return out


_EPOCH = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


class DateProcessor(Processor):
    """strptime into an epoch-ns timestamp (reference processor/date.rs)."""

    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.formats = cfg.get("formats") or ["%Y-%m-%dT%H:%M:%S%z"]
        if isinstance(self.formats, str):
            self.formats = [self.formats]
        tz = cfg.get("timezone")
        self.tz = None
        if tz:
            off = re.match(r"^([+-])(\d{2}):?(\d{2})$", str(tz))
            if off:
                sign = 1 if off.group(1) == "+" else -1
                self.tz = datetime.timezone(
                    sign * datetime.timedelta(hours=int(off.group(2)), minutes=int(off.group(3)))
                )
            elif str(tz).upper() in ("UTC", "Z"):
                self.tz = datetime.timezone.utc
            else:
                try:
                    import zoneinfo

                    self.tz = zoneinfo.ZoneInfo(str(tz))
                except (zoneinfo.ZoneInfoNotFoundError, ValueError) as e:
                    raise PipelineParseError(f"date: unknown timezone {tz!r}") from e

    def apply(self, doc: dict, src: str, dst: str):
        text = str(doc[src])
        for fmt in self.formats:
            try:
                dt = datetime.datetime.strptime(text, fmt)
            except ValueError:
                continue
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=self.tz or datetime.timezone.utc)
            # exact integer arithmetic: float timestamp() truncation can be
            # off by 1us on ~1% of fractional-second inputs
            delta = dt - _EPOCH
            doc[dst] = TsNs(
                (delta.days * 86_400 + delta.seconds) * 1_000_000_000
                + delta.microseconds * 1_000
            )
            return
        raise PipelineExecError(f"date: {text!r} matches none of {self.formats}")


class EpochProcessor(Processor):
    """Numeric epoch at s/ms/us/ns resolution -> epoch-ns
    (reference processor/epoch.rs)."""

    _FACTOR = {"s": 1_000_000_000, "second": 1_000_000_000,
               "ms": 1_000_000, "millisecond": 1_000_000, "milli": 1_000_000,
               "us": 1_000, "microsecond": 1_000, "micro": 1_000,
               "ns": 1, "nanosecond": 1, "nano": 1}

    def __init__(self, cfg: dict):
        super().__init__(cfg)
        res = str(cfg.get("resolution", "ms"))
        if res not in self._FACTOR:
            raise PipelineParseError(f"epoch: unknown resolution {res!r}")
        self.factor = self._FACTOR[res]

    def apply(self, doc: dict, src: str, dst: str):
        v = doc[src]
        try:
            # int first: going through float would lose precision on ns
            # epochs beyond 2^53
            n = int(v)
        except (TypeError, ValueError):
            try:
                n = int(float(v))
            except (TypeError, ValueError) as e:
                raise PipelineExecError(f"epoch: {v!r} is not numeric") from e
        doc[dst] = TsNs(n * self.factor)


class CsvProcessor(Processor):
    def __init__(self, cfg: dict):
        super().__init__(cfg)
        tf = cfg.get("target_fields", "")
        self.target_fields = (
            [s.strip() for s in tf.split(",")] if isinstance(tf, str) else list(tf)
        )
        self.separator = str(cfg.get("separator", ","))
        self.quote = str(cfg.get("quote", '"'))
        self.trim = bool(cfg.get("trim", False))
        self.empty_value = cfg.get("empty_value")

    def apply(self, doc: dict, src: str, dst: str):
        import csv as _csv
        import io

        reader = _csv.reader(
            io.StringIO(str(doc[src])), delimiter=self.separator, quotechar=self.quote
        )
        row = next(reader, [])
        for name, value in zip(self.target_fields, row):
            if self.trim:
                value = value.strip()
            if value == "" and self.empty_value is not None:
                value = self.empty_value
            doc[name] = value


class RegexProcessor(Processor):
    """Named-group extraction; outputs <field>_<group>
    (reference processor/regex.rs)."""

    def __init__(self, cfg: dict):
        super().__init__(cfg)
        patterns = cfg.get("patterns") or ([cfg["pattern"]] if "pattern" in cfg else [])
        if not patterns:
            raise PipelineParseError("regex requires patterns")
        # the DSL uses (?<name>...) like Rust/PCRE; Python wants (?P<name>...)
        self.patterns = [re.compile(re.sub(r"\(\?<([A-Za-z_]\w*)>", r"(?P<\1>", p)) for p in patterns]

    def apply(self, doc: dict, src: str, dst: str):
        text = str(doc[src])
        for rx in self.patterns:
            m = rx.search(text)
            if m:
                for name, value in m.groupdict().items():
                    if value is not None:
                        doc[f"{dst}_{name}"] = value
                return


class GsubProcessor(Processor):
    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.pattern = re.compile(str(cfg.get("pattern", "")))
        self.replacement = str(cfg.get("replacement", ""))

    def apply(self, doc: dict, src: str, dst: str):
        doc[dst] = self.pattern.sub(self.replacement, str(doc[src]))


class JoinProcessor(Processor):
    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.separator = str(cfg.get("separator", ","))

    def apply(self, doc: dict, src: str, dst: str):
        v = doc[src]
        if isinstance(v, (list, tuple)):
            doc[dst] = self.separator.join(str(x) for x in v)


class LetterProcessor(Processor):
    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.method = str(cfg.get("method", "lower")).lower()

    def apply(self, doc: dict, src: str, dst: str):
        s = str(doc[src])
        if self.method == "upper":
            doc[dst] = s.upper()
        elif self.method == "capital":
            doc[dst] = s.capitalize()
        else:
            doc[dst] = s.lower()


class UrlEncodingProcessor(Processor):
    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.method = str(cfg.get("method", "decode")).lower()

    def apply(self, doc: dict, src: str, dst: str):
        s = str(doc[src])
        doc[dst] = (
            urllib.parse.quote(s) if self.method == "encode" else urllib.parse.unquote(s)
        )


class JsonParseProcessor(Processor):
    def apply(self, doc: dict, src: str, dst: str):
        try:
            doc[dst] = json.loads(str(doc[src]))
        except json.JSONDecodeError as e:
            raise PipelineExecError(f"json_parse: invalid JSON in {src!r}: {e}") from e


class SimpleExtractProcessor(Processor):
    """Dot-path extraction from a parsed JSON value
    (reference processor/simple_extract.rs)."""

    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.key = str(cfg.get("key", ""))

    def apply(self, doc: dict, src: str, dst: str):
        v = doc[src]
        for part in self.key.split(".") if self.key else []:
            if isinstance(v, dict) and part in v:
                v = v[part]
            else:
                if self.ignore_missing:
                    return
                raise PipelineExecError(f"simple_extract: key {self.key!r} not found")
        doc[dst] = v


class DecolorizeProcessor(Processor):
    _ANSI = re.compile(r"\x1b\[[0-9;]*m")

    def apply(self, doc: dict, src: str, dst: str):
        doc[dst] = self._ANSI.sub("", str(doc[src]))


class DigestProcessor(Processor):
    """Strip variable content (numbers, uuids, ips, quoted strings, brackets)
    to a stable template in <field>_digest (reference processor/digest.rs)."""

    _PRESETS = {
        "numbers": re.compile(r"\d+(\.\d+)?"),
        "uuid": re.compile(
            r"[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}"
        ),
        "ip": re.compile(r"((\d{1,3}\.){3}\d{1,3}(:\d+)?)|(\[[0-9a-fA-F:]+\](:\d+)?)"),
        "quoted": re.compile(r"\"[^\"]*\"|'[^']*'"),
        "bracketed": re.compile(r"\[[^\[\]]*\]|\{[^{}]*\}|<[^<>]*>"),
    }

    def __init__(self, cfg: dict):
        super().__init__(cfg)
        presets = cfg.get("presets", ["numbers", "uuid", "ip", "quoted", "bracketed"])
        self.patterns = [self._PRESETS[p] for p in presets if p in self._PRESETS]
        for extra in cfg.get("regex", []) or []:
            self.patterns.append(re.compile(extra))

    def apply(self, doc: dict, src: str, dst: str):
        s = str(doc[src])
        for rx in self.patterns:
            s = rx.sub("", s)
        doc[f"{dst}_digest"] = s


class SelectProcessor(Processor):
    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.mode = str(cfg.get("type", "include")).lower()

    def __call__(self, doc: dict):
        names = [src for src, _ in self.fields]
        if self.mode == "exclude":
            for n in names:
                doc.pop(n, None)
        else:
            keep = set(names)
            for n in list(doc.keys()):
                if n not in keep:
                    del doc[n]

    def apply(self, doc: dict, src: str, dst: str):  # pragma: no cover
        pass


class FilterProcessor(Processor):
    """Drop documents whose field matches/doesn't match targets
    (reference processor/filter.rs)."""

    def __init__(self, cfg: dict):
        super().__init__(cfg)
        self.targets = [str(t) for t in (cfg.get("targets") or [])]
        self.match_op = str(cfg.get("match_op", "in")).lower()
        self.case_insensitive = bool(cfg.get("case_insensitive", True))
        if self.case_insensitive:
            self.targets = [t.lower() for t in self.targets]

    def apply(self, doc: dict, src: str, dst: str):
        v = str(doc[src])
        if self.case_insensitive:
            v = v.lower()
        hit = v in self.targets
        if (self.match_op == "in" and hit) or (self.match_op == "not_in" and not hit):
            raise DropDocument()


PROCESSORS = {
    "dissect": DissectProcessor,
    "date": DateProcessor,
    "epoch": EpochProcessor,
    "csv": CsvProcessor,
    "regex": RegexProcessor,
    "gsub": GsubProcessor,
    "join": JoinProcessor,
    "letter": LetterProcessor,
    "urlencoding": UrlEncodingProcessor,
    "json_parse": JsonParseProcessor,
    "simple_extract": SimpleExtractProcessor,
    "decolorize": DecolorizeProcessor,
    "digest": DigestProcessor,
    "select": SelectProcessor,
    "filter": FilterProcessor,
}


# ---- transform --------------------------------------------------------------

_TYPE_ALIASES = {
    "int8": ConcreteDataType.INT8, "int16": ConcreteDataType.INT16,
    "int32": ConcreteDataType.INT32, "int64": ConcreteDataType.INT64,
    "uint8": ConcreteDataType.UINT8, "uint16": ConcreteDataType.UINT16,
    "uint32": ConcreteDataType.UINT32, "uint64": ConcreteDataType.UINT64,
    "float32": ConcreteDataType.FLOAT32, "float64": ConcreteDataType.FLOAT64,
    "string": ConcreteDataType.STRING, "boolean": ConcreteDataType.BOOLEAN,
    "bool": ConcreteDataType.BOOLEAN, "json": ConcreteDataType.JSON,
}
_TS_UNITS = {
    "s": ConcreteDataType.TIMESTAMP_SECOND, "sec": ConcreteDataType.TIMESTAMP_SECOND,
    "ms": ConcreteDataType.TIMESTAMP_MILLISECOND,
    "us": ConcreteDataType.TIMESTAMP_MICROSECOND,
    "ns": ConcreteDataType.TIMESTAMP_NANOSECOND,
}


@dataclass
class TransformRule:
    fields: list[tuple[str, str]]
    dtype: ConcreteDataType
    index: str | None = None  # time | tag | fulltext | inverted | skip
    on_failure: str | None = None  # ignore | default
    default: object = None

    @classmethod
    def parse(cls, cfg: dict) -> "TransformRule":
        fields = [_split_rename(f) for f in _as_fields(cfg)]
        tspec = str(cfg.get("type", "string")).strip()
        if tspec.startswith("timestamp"):
            parts = [p.strip() for p in tspec.split(",")]
            unit = parts[1] if len(parts) > 1 else "ms"
            dtype = _TS_UNITS.get(unit, ConcreteDataType.TIMESTAMP_MILLISECOND)
        elif tspec.startswith("epoch"):
            parts = [p.strip() for p in tspec.split(",")]
            unit = parts[1] if len(parts) > 1 else "ms"
            dtype = _TS_UNITS.get(unit, ConcreteDataType.TIMESTAMP_MILLISECOND)
        elif tspec in _TYPE_ALIASES:
            dtype = _TYPE_ALIASES[tspec]
        else:
            raise PipelineParseError(f"transform: unknown type {tspec!r}")
        return cls(
            fields=fields,
            dtype=dtype,
            index=cfg.get("index"),
            on_failure=cfg.get("on_failure"),
            default=cfg.get("default"),
        )

    def convert(self, v):
        try:
            if v is None:
                raise ValueError("null")
            if self.dtype.is_timestamp():
                if isinstance(v, TsNs):
                    # a date/epoch processor normalized to epoch-ns;
                    # rescale to the declared unit
                    return int(v) // self.dtype.timestamp_unit_ns()
                # raw field: the declared unit IS the input unit
                # (reference `type: epoch, ms` semantics)
                return int(v)
            if self.dtype == ConcreteDataType.BOOLEAN:
                if isinstance(v, str):
                    return v.lower() in ("1", "t", "true", "yes")
                return bool(v)
            if self.dtype in (ConcreteDataType.FLOAT32, ConcreteDataType.FLOAT64):
                return float(v)
            if self.dtype in (ConcreteDataType.STRING,):
                return v if isinstance(v, str) else json.dumps(v, default=str)
            if self.dtype == ConcreteDataType.JSON:
                return v if isinstance(v, str) else json.dumps(v, default=str)
            return int(v)
        except (TypeError, ValueError) as e:
            if self.on_failure == "ignore":
                return None
            if self.on_failure == "default":
                return self.default
            raise PipelineExecError(
                f"transform: cannot convert {v!r} to {self.dtype.value}"
            ) from e


@dataclass
class DispatcherRule:
    value: str
    table_suffix: str | None = None
    pipeline: str | None = None


@dataclass
class Dispatcher:
    field: str
    rules: list[DispatcherRule]

    def route(self, doc: dict) -> DispatcherRule | None:
        v = doc.get(self.field)
        if v is None:
            return None
        for r in self.rules:
            if str(v) == r.value:
                return r
        return None


# ---- pipeline ---------------------------------------------------------------


@dataclass
class Pipeline:
    name: str
    processors: list[Processor] = field(default_factory=list)
    transforms: list[TransformRule] = field(default_factory=list)
    dispatcher: Dispatcher | None = None
    description: str = ""
    source: str = ""

    def exec_doc(self, doc: dict):
        """Run one document; returns (row_dict, dispatcher_rule | None) or
        None if the document was filtered out.  row_dict maps column name ->
        (value, ConcreteDataType, index)."""
        doc = dict(doc)
        try:
            for p in self.processors:
                p(doc)
        except DropDocument:
            return None
        rule = self.dispatcher.route(doc) if self.dispatcher else None
        if rule is not None and rule.pipeline:
            return (doc, rule)  # re-dispatched: caller runs the named pipeline
        if self.transforms:
            row: dict = {}
            for t in self.transforms:
                for src, dst in t.fields:
                    row[dst] = (t.convert(doc.get(src)), t.dtype, t.index)
            return (row, rule)
        return (identity_row(doc), rule)


def identity_row(doc: dict) -> dict:
    """Auto-type every field (the greptime_identity pipeline, reference
    etl/transform/transformer/greptime.rs identity_pipeline)."""
    row: dict = {}
    for k, v in doc.items():
        if isinstance(v, bool):
            row[k] = (v, ConcreteDataType.BOOLEAN, None)
        elif isinstance(v, int):
            row[k] = (v, ConcreteDataType.INT64, None)
        elif isinstance(v, float):
            row[k] = (v, ConcreteDataType.FLOAT64, None)
        elif isinstance(v, (dict, list)):
            row[k] = (json.dumps(v, default=str), ConcreteDataType.JSON, None)
        elif v is None:
            row[k] = (None, ConcreteDataType.STRING, None)
        else:
            row[k] = (str(v), ConcreteDataType.STRING, None)
    return row


def parse_pipeline(yaml_text: str, name: str = "") -> Pipeline:
    import yaml as _yaml

    try:
        spec = _yaml.safe_load(yaml_text)
    except _yaml.YAMLError as e:
        raise PipelineParseError(f"invalid pipeline YAML: {e}") from e
    if not isinstance(spec, dict):
        raise PipelineParseError("pipeline YAML must be a mapping")
    processors: list[Processor] = []
    for item in spec.get("processors") or []:
        if not isinstance(item, dict) or len(item) != 1:
            raise PipelineParseError(f"bad processor entry: {item!r}")
        ptype, cfg = next(iter(item.items()))
        if ptype not in PROCESSORS:
            raise PipelineParseError(f"unknown processor {ptype!r}")
        processors.append(PROCESSORS[ptype](cfg or {}))
    transforms = [
        TransformRule.parse(t) for t in (spec.get("transform") or spec.get("transforms") or [])
    ]
    dispatcher = None
    if "dispatcher" in spec:
        d = spec["dispatcher"] or {}
        if "field" not in d:
            raise PipelineParseError("dispatcher requires a field")
        dispatcher = Dispatcher(
            field=str(d["field"]),
            rules=[
                DispatcherRule(
                    value=str(r.get("value")),
                    table_suffix=r.get("table_suffix"),
                    pipeline=r.get("pipeline"),
                )
                for r in (d.get("rules") or [])
            ],
        )
    n_time = sum(1 for t in transforms if t.index == "time")
    if n_time > 1:
        raise PipelineParseError("at most one transform field may be index: time")
    return Pipeline(
        name=name,
        processors=processors,
        transforms=transforms,
        dispatcher=dispatcher,
        description=str(spec.get("description", "")),
        source=yaml_text,
    )
