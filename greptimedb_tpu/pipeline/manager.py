"""Pipeline manager: versioned definitions + the ingest path.

Role-equivalent of the reference's manager module (reference
src/pipeline/src/manager/pipeline_operator.rs): pipelines are stored
versioned (created-at-ms version keys, latest wins), the built-in
`greptime_identity` pipeline auto-types documents, and `run_pipeline_ingest`
turns documents into typed rows and writes them to (possibly
dispatcher-suffixed) tables.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pyarrow as pa

from ..datatypes.data_type import ConcreteDataType
from ..datatypes.schema import ColumnSchema, Schema, SemanticType
from ..utils.errors import GreptimeError, InvalidArgumentsError, StatusCode
from .etl import Pipeline, identity_row, parse_pipeline

GREPTIME_IDENTITY = "greptime_identity"
DEFAULT_TS_COLUMN = "greptime_timestamp"


class PipelineNotFoundError(GreptimeError):
    def status_code(self) -> StatusCode:
        return StatusCode.INVALID_ARGUMENTS


class PipelineManager:
    """Versioned pipeline store persisted next to the catalog (the reference
    keeps them in the greptime_private.pipelines system table)."""

    def __init__(self, data_home: str):
        self._path = os.path.join(data_home, "pipelines.json")
        self._lock = threading.Lock()
        # name -> {version_ms(str) -> yaml}
        self._store: dict[str, dict[str, str]] = {}
        self._cache: dict[tuple[str, str], Pipeline] = {}
        if os.path.exists(self._path):
            with open(self._path) as f:
                self._store = json.load(f)

    def _persist(self):
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._store, f)
        os.replace(tmp, self._path)

    def save(self, name: str, yaml_text: str) -> str:
        """Validate + store a new version; returns the version key."""
        if name == GREPTIME_IDENTITY:
            raise InvalidArgumentsError(f"{GREPTIME_IDENTITY} is reserved")
        parse_pipeline(yaml_text, name)  # validate before storing
        version = str(int(time.time() * 1000))
        with self._lock:
            versions = self._store.setdefault(name, {})
            while version in versions:  # same-ms saves
                version = str(int(version) + 1)
            versions[version] = yaml_text
            self._persist()
        return version

    def get(self, name: str, version: str | None = None) -> Pipeline:
        if name == GREPTIME_IDENTITY:
            return Pipeline(name=GREPTIME_IDENTITY)
        with self._lock:
            versions = self._store.get(name)
            if not versions:
                raise PipelineNotFoundError(f"pipeline not found: {name}")
            v = version or max(versions, key=int)
            yaml_text = versions.get(v)
            if yaml_text is None:
                raise PipelineNotFoundError(f"pipeline {name} has no version {version}")
            key = (name, v)
            if key not in self._cache:
                self._cache[key] = parse_pipeline(yaml_text, name)
            return self._cache[key]

    def delete(self, name: str, version: str | None = None):
        with self._lock:
            if name not in self._store:
                raise PipelineNotFoundError(f"pipeline not found: {name}")
            if version is None:
                del self._store[name]
            else:
                self._store[name].pop(version, None)
                if not self._store[name]:
                    del self._store[name]
            self._cache = {k: v for k, v in self._cache.items() if k[0] != name}
            self._persist()

    def list(self) -> list[tuple[str, str]]:
        with self._lock:
            return sorted(
                (name, max(vs, key=int)) for name, vs in self._store.items() if vs
            )


_PIPELINES_INIT_LOCK = threading.Lock()


def _pipelines(db) -> PipelineManager:
    mgr = getattr(db, "_pipeline_manager", None)
    if mgr is None:
        with _PIPELINES_INIT_LOCK:
            mgr = getattr(db, "_pipeline_manager", None)
            if mgr is None:
                mgr = PipelineManager(db.config.storage.data_home)
                db._pipeline_manager = mgr
    return mgr


def run_pipeline_ingest(
    db,
    pipeline_name: str,
    docs: list[dict],
    table: str,
    database: str = "public",
    version: str | None = None,
    max_depth: int = 4,
) -> int:
    """Execute a pipeline over documents and insert the rows.

    Dispatcher rules may fan documents out to `<table>_<suffix>` and/or
    another pipeline (depth-limited, reference dispatcher.rs)."""
    mgr = _pipelines(db)
    pipeline = mgr.get(pipeline_name, version)
    # (table, pipeline) -> rows
    grouped: dict[str, list[dict]] = {}
    redispatch: dict[tuple[str, str], list[dict]] = {}
    for doc in docs:
        out = pipeline.exec_doc(doc)
        if out is None:
            continue  # filtered
        row_or_doc, rule = out
        if rule is not None and rule.pipeline:
            if max_depth <= 0:
                raise InvalidArgumentsError("pipeline dispatcher recursion too deep")
            target = f"{table}_{rule.table_suffix}" if rule.table_suffix else table
            redispatch.setdefault((rule.pipeline, target), []).append(row_or_doc)
            continue
        target = f"{table}_{rule.table_suffix}" if rule and rule.table_suffix else table
        grouped.setdefault(target, []).append(row_or_doc)
    total = 0
    for target, rows in grouped.items():
        total += _write_rows(db, target, rows, database)
    for (pname, target), subdocs in redispatch.items():
        total += run_pipeline_ingest(
            db, pname, subdocs, target, database, max_depth=max_depth - 1
        )
    return total


def _write_rows(db, table: str, rows: list[dict], database: str) -> int:
    """rows: [{col -> (value, dtype, index)}] -> ensure table + insert."""
    from ..servers.otlp import ensure_table

    # Union the column layout over all rows (identity pipelines can vary).
    layout: dict[str, tuple[ConcreteDataType, str | None]] = {}
    for row in rows:
        for name, (_v, dtype, index) in row.items():
            if name not in layout:
                layout[name] = (dtype, index)
            elif layout[name][0] != dtype:
                layout[name] = (_widen(layout[name][0], dtype), layout[name][1])
    has_time = any(index == "time" for _d, index in layout.values())
    if not has_time:
        # identity pipelines get an ingestion-time ns column (reference
        # identity_pipeline's greptime_timestamp)
        layout[DEFAULT_TS_COLUMN] = (ConcreteDataType.TIMESTAMP_NANOSECOND, "time")
        now_ns = time.time_ns()
        for i, row in enumerate(rows):
            # distinct per-row ns so rows without tags don't dedup-collapse
            row[DEFAULT_TS_COLUMN] = (
                now_ns + i, ConcreteDataType.TIMESTAMP_NANOSECOND, "time",
            )
    columns = []
    for name, (dtype, index) in layout.items():
        if index == "time":
            sem = SemanticType.TIMESTAMP
        elif index == "tag":
            sem = SemanticType.TAG
        else:
            sem = SemanticType.FIELD
        columns.append(
            ColumnSchema(
                name,
                dtype,
                sem,
                nullable=sem == SemanticType.FIELD,
                default="" if sem == SemanticType.TAG else None,
            )
        )
    schema = Schema(columns=columns)
    # Widening an existing table's schema is a read-modify-write on shared
    # catalog state; concurrent ingest threads (ThreadingHTTPServer) would
    # otherwise lose columns, so serialize under the db DDL lock. Regions
    # are altered before the catalog publishes the widened schema so a
    # concurrent query never sees a column the regions lack.
    with db.ddl_lock:
        meta = ensure_table(db, table, schema, database)
        missing = [c for c in columns if not meta.schema.has_column(c.name)]
        if missing:
            widened = meta.schema
            for c in missing:
                widened = widened.add_column(c)
            for rid in meta.region_ids:
                db.storage.region(rid).alter_schema(widened)
            meta.schema = widened
            db.catalog.update_table(meta)
    arrays = {}
    for col in meta.schema.columns:
        dt = col.data_type
        vals = []
        for row in rows:
            v = row.get(col.name, (None, None, None))[0]
            if v is None and col.semantic_type == SemanticType.TAG:
                v = ""
            vals.append(_coerce(v, dt, col.name))
        arrays[col.name] = pa.array(vals, dt.to_arrow())
    return db.insert_rows(meta.name, pa.table(arrays), database=database)


def _widen(a: ConcreteDataType, b: ConcreteDataType) -> ConcreteDataType:
    """Least common type for a cross-document conflict: numerics widen to
    float64 when a float is involved (int64 otherwise), anything else
    falls back to string."""
    if a == b:
        return a
    if a.is_numeric() and b.is_numeric():
        if a.is_float() or b.is_float():
            return ConcreteDataType.FLOAT64
        return ConcreteDataType.INT64
    return ConcreteDataType.STRING


def _coerce(v, dt: ConcreteDataType, col: str):
    """Convert a document value to an existing column's type, raising a
    client error (HTTP 400) instead of crashing or silently truncating."""
    if v is None:
        return None
    try:
        if dt in (ConcreteDataType.STRING, ConcreteDataType.JSON):
            return v if isinstance(v, str) else json.dumps(v, default=str)
        if dt == ConcreteDataType.BOOLEAN:
            return bool(v)
        if dt.is_float():
            return float(v)
        if dt.is_timestamp():
            return int(v)
        # integer column: a fractional float would silently truncate
        if isinstance(v, float) and v != int(v):
            raise ValueError("fractional value in integer column")
        return int(v)
    except (TypeError, ValueError) as e:
        raise InvalidArgumentsError(
            f"cannot store {v!r} into column {col!r} of type {dt.value} "
            "(existing table schema wins; adjust the pipeline transform)"
        ) from e
