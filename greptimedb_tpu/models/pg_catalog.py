"""pg_catalog: PostgreSQL system-catalog compatibility tables.

Role-equivalent of the reference's pg_catalog virtual schema (reference
catalog/src/system_schema/pg_catalog.rs + pg_catalog/): enough of
pg_class / pg_namespace / pg_type / pg_database for BI tools and drivers
that probe the PG catalog over the PostgreSQL wire protocol.

Synthesized from the live catalog on every scan, like information_schema.
"""

from __future__ import annotations

import pyarrow as pa

from ..datatypes.data_type import ConcreteDataType
from ..datatypes.schema import ColumnSchema, Schema, SemanticType

PG_CATALOG = "pg_catalog"

# Stable synthetic OID spaces (the reference derives oids by hashing names;
# here: namespace oids are enumeration-ordered, table oids reuse table_id).
_NS_BASE = 2200
_TYPE_OIDS = {
    "bool": (16, 1),
    "int8": (20, 8),
    "int4": (23, 4),
    "float4": (700, 4),
    "float8": (701, 8),
    "text": (25, -1),
    "varchar": (1043, -1),
    "timestamp": (1114, 8),
    "timestamptz": (1184, 8),
    "date": (1082, 4),
    "numeric": (1700, -1),
    "bytea": (17, -1),
    "json": (114, -1),
}


def is_pg_catalog(database: str) -> bool:
    return database.lower() == PG_CATALOG


def build(db, table: str) -> pa.Table:
    fn = _TABLES.get(table.lower())
    if fn is None:
        from ..utils.errors import TableNotFoundError

        raise TableNotFoundError(f"pg_catalog has no table {table!r}")
    return fn(db)


def schema_of(db, table: str) -> Schema:
    t = build(db, table)
    return Schema(
        columns=[
            ColumnSchema(f.name, ConcreteDataType.from_arrow(f.type), SemanticType.FIELD)
            for f in t.schema
        ]
    )


def _ns_oids(db) -> dict[str, int]:
    return {name: _NS_BASE + i for i, name in enumerate(sorted(db.catalog.databases()))}


def _pg_namespace(db) -> pa.Table:
    ns = _ns_oids(db)
    names = sorted(ns)
    return pa.table(
        {
            "oid": pa.array([ns[n] for n in names], pa.int64()),
            "nspname": names,
        }
    )


def _pg_class(db) -> pa.Table:
    ns = _ns_oids(db)
    rows = {"oid": [], "relname": [], "relnamespace": [], "relkind": [], "relowner": []}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            rows["oid"].append(meta.table_id)
            rows["relname"].append(meta.name)
            rows["relnamespace"].append(ns[database])
            rows["relkind"].append("r")
            rows["relowner"].append(10)
        for i, vname in enumerate(sorted(db.catalog.views(database))):
            rows["oid"].append(1_000_000 + ns[database] * 1000 + i)
            rows["relname"].append(vname)
            rows["relnamespace"].append(ns[database])
            rows["relkind"].append("v")
            rows["relowner"].append(10)
    return pa.table(
        {
            "oid": pa.array(rows["oid"], pa.int64()),
            "relname": rows["relname"],
            "relnamespace": pa.array(rows["relnamespace"], pa.int64()),
            "relkind": rows["relkind"],
            "relowner": pa.array(rows["relowner"], pa.int64()),
        }
    )


def _pg_type(db) -> pa.Table:
    names = sorted(_TYPE_OIDS)
    return pa.table(
        {
            "oid": pa.array([_TYPE_OIDS[n][0] for n in names], pa.int64()),
            "typname": names,
            "typlen": pa.array([_TYPE_OIDS[n][1] for n in names], pa.int64()),
        }
    )


def _pg_database(db) -> pa.Table:
    names = sorted(db.catalog.databases())
    ns = _ns_oids(db)
    return pa.table(
        {
            "oid": pa.array([ns[n] for n in names], pa.int64()),
            "datname": names,
        }
    )


_TABLES = {
    "pg_class": _pg_class,
    "pg_namespace": _pg_namespace,
    "pg_type": _pg_type,
    "pg_database": _pg_database,
}


def table_names() -> list[str]:
    return sorted(_TABLES)
