"""information_schema: queryable system introspection tables.

Role-equivalent of the reference's virtual system schema (reference
catalog/src/system_schema/information_schema/: tables, columns,
region_statistics, cluster_info, engines, procedure_info...): synthesized
from the catalog + storage engine on every scan, so `SELECT * FROM
information_schema.tables` always reflects live state.
"""

from __future__ import annotations

import pyarrow as pa

from ..datatypes.data_type import ConcreteDataType
from ..datatypes.schema import ColumnSchema, Schema, SemanticType

INFORMATION_SCHEMA = "information_schema"


def is_information_schema(database: str) -> bool:
    return database.lower() == INFORMATION_SCHEMA


def build(db, table: str) -> pa.Table:
    fn = _TABLES.get(table.lower())
    if fn is None:
        from ..utils.errors import TableNotFoundError

        raise TableNotFoundError(f"information_schema has no table {table!r}")
    return fn(db)


def schema_of(db, table: str) -> Schema:
    # runtime-introspection tables can be LARGE (the dispatch ring, the
    # per-plane cache walk under the cache lock): schema questions
    # (DESCRIBE, planning) build their EMPTY twin instead of
    # materializing state that is discarded after reading .schema
    empty = _EMPTY_TABLES.get(table.lower())
    t = empty() if empty is not None else build(db, table)
    return Schema(
        columns=[
            ColumnSchema(f.name, ConcreteDataType.from_arrow(f.type), SemanticType.FIELD)
            for f in t.schema
        ]
    )


def _tables(db) -> pa.Table:
    rows = {"table_catalog": [], "table_schema": [], "table_name": [], "table_id": [],
            "table_type": [], "engine": [], "region_count": []}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            rows["table_catalog"].append("greptime")
            rows["table_schema"].append(database)
            rows["table_name"].append(meta.name)
            rows["table_id"].append(meta.table_id)
            rows["table_type"].append("BASE TABLE")
            rows["engine"].append(meta.options.get("engine", "mito"))
            rows["region_count"].append(len(meta.region_ids))
    return pa.table(rows)


def _columns(db) -> pa.Table:
    rows = {"table_schema": [], "table_name": [], "column_name": [], "data_type": [],
            "semantic_type": [], "is_nullable": [], "column_default": []}
    rows["column_key"] = []
    sem_names = {SemanticType.TAG: "TAG", SemanticType.FIELD: "FIELD", SemanticType.TIMESTAMP: "TIMESTAMP"}
    # column_key mirrors the reference's columns view (information_schema
    # columns.rs): PRI for primary-key members, TIME INDEX for the time
    # index, empty for fields
    keys = {SemanticType.TAG: "PRI", SemanticType.TIMESTAMP: "TIME INDEX",
            SemanticType.FIELD: ""}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            for c in meta.schema.columns:
                rows["table_schema"].append(database)
                rows["table_name"].append(meta.name)
                rows["column_name"].append(c.name)
                rows["data_type"].append(c.data_type.value)
                rows["semantic_type"].append(sem_names[c.semantic_type])
                rows["is_nullable"].append("YES" if c.nullable else "NO")
                rows["column_default"].append(str(c.default) if c.default is not None else None)
                rows["column_key"].append(keys[c.semantic_type])
    return pa.table(rows)


def _region_statistics(db) -> pa.Table:
    rows = {"region_id": [], "table_id": [], "region_rows": [], "disk_size": [],
            "memtable_size": [], "sst_num": [], "wal_entry_id": [], "flushed_entry_id": []}
    for stat in db.storage.region_statistics():
        rows["region_id"].append(stat.region_id)
        rows["table_id"].append(stat.region_id // 1024)
        rows["region_rows"].append(stat.num_rows)
        rows["disk_size"].append(stat.sst_bytes)
        rows["memtable_size"].append(stat.memtable_bytes)
        rows["sst_num"].append(stat.sst_count)
        rows["wal_entry_id"].append(stat.wal_entry_id)
        rows["flushed_entry_id"].append(stat.flushed_entry_id)
    return pa.table(rows)


def _engines(db) -> pa.Table:
    return pa.table(
        {
            "engine": ["mito", "metric", "file"],
            "support": ["DEFAULT", "YES", "YES"],
            "comment": [
                "TPU-native LSM time-series engine",
                "logical-table multiplexer over mito",
                "external-file tables",
            ],
        }
    )


def _region_peers(db) -> pa.Table:
    """information_schema.region_peers (reference
    common/catalog information_schema/region_peers.rs): one row per
    region with its hosting peer; standalone hosts everything on peer 0."""
    rows = {"table_catalog": [], "table_schema": [], "table_name": [],
            "region_id": [], "peer_id": [], "peer_addr": [], "is_leader": [],
            "status": []}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            for rid in meta.region_ids:
                rows["table_catalog"].append("greptime")
                rows["table_schema"].append(database)
                rows["table_name"].append(meta.name)
                rows["region_id"].append(rid)
                rows["peer_id"].append(0)
                rows["peer_addr"].append("")
                rows["is_leader"].append("Yes")
                rows["status"].append("ALIVE")
    return pa.table(rows)


def _cluster_info(db) -> pa.Table:
    from .. import __version__

    return pa.table(
        {
            "peer_id": [0],
            "peer_type": ["STANDALONE"],
            "peer_addr": [""],
            "version": [__version__],
            "active_time": [""],
        }
    )


def _schemata(db) -> pa.Table:
    dbs = db.catalog.databases()
    return pa.table(
        {
            "catalog_name": ["greptime"] * len(dbs),
            "schema_name": dbs,
        }
    )


def _partitions(db) -> pa.Table:
    rows = {"table_schema": [], "table_name": [], "partition_name": [], "partition_expression": [],
            "greptime_partition_id": []}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            rule = meta.partition_rule.to_dict()
            for i, rid in enumerate(meta.region_ids):
                rows["table_schema"].append(database)
                rows["table_name"].append(meta.name)
                rows["partition_name"].append(f"p{i}")
                rows["partition_expression"].append(str(rule))
                rows["greptime_partition_id"].append(rid)
    return pa.table(rows)


def _flows(db) -> pa.Table:
    """information_schema.flows (reference
    catalog/src/system_schema/information_schema/flows.rs)."""
    infos = db.flows.list_flows() if hasattr(db, "flows") else []
    return pa.table(
        {
            "flow_name": [i.name for i in infos],
            "flow_id": [i.flow_id for i in infos],
            "state_size": [0 for _ in infos],
            "table_catalog": ["greptime" for _ in infos],
            "flow_definition": [i.sql for i in infos],
            "comment": [i.comment or "" for i in infos],
            "expire_after": [i.expire_after_ms for i in infos],
            "source_table_names": [i.source_table for i in infos],
            "sink_table_name": [i.sink_table for i in infos],
            "options": [i.mode for i in infos],
        }
    )


def _views(db) -> pa.Table:
    """information_schema.views (reference
    catalog/src/system_schema/information_schema/views.rs)."""
    rows = {"table_catalog": [], "table_schema": [], "table_name": [], "view_definition": []}
    for database in db.catalog.databases():
        for name, sql_text in sorted(db.catalog.views(database).items()):
            rows["table_catalog"].append("greptime")
            rows["table_schema"].append(database)
            rows["table_name"].append(name)
            rows["view_definition"].append(sql_text)
    return pa.table(
        {k: pa.array(v, pa.string()) for k, v in rows.items()}
    )


def _process_list(db) -> pa.Table:
    """information_schema.process_list (reference
    catalog/src/system_schema/information_schema/process_list.rs)."""
    procs = db.process_manager.list() if hasattr(db, "process_manager") else []
    addr = db.process_manager.server_addr if procs else "standalone"
    return pa.table(
        {
            "id": pa.array([f"{addr}/{p.process_id}" for p in procs], pa.string()),
            "catalog": pa.array(["greptime" for _ in procs], pa.string()),
            "schemas": pa.array([p.database for p in procs], pa.string()),
            "query": pa.array([p.query for p in procs], pa.string()),
            "client": pa.array([p.client for p in procs], pa.string()),
            "frontend": pa.array([addr for _ in procs], pa.string()),
            "start_timestamp": pa.array(
                [p.start_time_ms for p in procs], pa.timestamp("ms")
            ),
            "elapsed_time": pa.array([p.elapsed_ms() for p in procs], pa.int64()),
        }
    )


def _table_of_region(db) -> dict:
    """region_id -> (database, table_name) reverse map."""
    out = {}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            for rid in meta.region_ids:
                out[rid] = (database, meta.name)
    return out


def _tile_cache(db):
    qe = getattr(db, "query_engine", None)
    return getattr(qe, "tile_cache", None)


def _tile_cache_entries(db) -> pa.Table:
    """information_schema.tile_cache_entries: one row per resident device
    plane of each region's super-tile (the runtime-introspection twin of
    region_statistics for the HBM tile cache).  Schema is a stable
    contract (README "Runtime introspection"); the entry walk is a
    single under-lock snapshot (TileCacheManager.introspect_entries)
    shared with /debug/tile."""
    rows = _tce_rows()
    cache = _tile_cache(db)
    if cache is not None:
        region_names = _table_of_region(db)
        for e in cache.introspect_entries():
            names = region_names.get(e["region_id"], ("", ""))
            for kind, plane, dev_b, host_b, chunks in e["planes"]:
                rows["table_schema"].append(names[0])
                rows["table_name"].append(names[1])
                rows["region_id"].append(e["region_id"])
                rows["plane"].append(plane)
                rows["kind"].append(kind)
                rows["state"].append(e["state"])
                rows["device_bytes"].append(dev_b)
                rows["host_bytes"].append(host_b)
                rows["rows"].append(e["rows"])
                rows["padded_rows"].append(e["padded_rows"])
                rows["chunks"].append(chunks)
                rows["delta_extends"].append(e["delta_extends"])
                rows["last_hit_ms"].append(e["last_hit_ms"])
    return _tce_table(rows)


def _tce_rows() -> dict:
    return {
        "table_schema": [], "table_name": [], "region_id": [], "plane": [],
        "kind": [], "state": [], "device_bytes": [], "host_bytes": [],
        "rows": [], "padded_rows": [], "chunks": [], "delta_extends": [],
        "last_hit_ms": [],
    }


def _tce_table(rows: dict) -> pa.Table:
    return pa.table({
        "table_schema": pa.array(rows["table_schema"], pa.string()),
        "table_name": pa.array(rows["table_name"], pa.string()),
        "region_id": pa.array(rows["region_id"], pa.int64()),
        "plane": pa.array(rows["plane"], pa.string()),
        "kind": pa.array(rows["kind"], pa.string()),
        "state": pa.array(rows["state"], pa.string()),
        "device_bytes": pa.array(rows["device_bytes"], pa.int64()),
        "host_bytes": pa.array(rows["host_bytes"], pa.int64()),
        "rows": pa.array(rows["rows"], pa.int64()),
        "padded_rows": pa.array(rows["padded_rows"], pa.int64()),
        "chunks": pa.array(rows["chunks"], pa.int64()),
        "delta_extends": pa.array(rows["delta_extends"], pa.int64()),
        "last_hit_ms": pa.array(rows["last_hit_ms"], pa.int64()),
    })


def _device_dispatches(db) -> pa.Table:
    """information_schema.device_dispatches: the flight-recorder ring —
    one row per tile dispatch (SQL tile / TQL tile / mesh table path),
    newest last.  Ghost rows are the background fused builder's priming
    dispatches; per-query views filter `ghost = 'false'`."""
    from ..utils.flight_recorder import RECORDER

    return _dispatch_table(RECORDER.snapshot())


def _dispatch_table(recs: list) -> pa.Table:
    import json as _json

    from ..utils.flight_recorder import STAGES

    cols: dict[str, list] = {
        "seq": [], "ts": [], "table_name": [], "trace_id": [], "plan_fp": [],
        "strategy": [], "build_mode": [], "mesh_devices": [],
        "compile_cache": [], "ghost": [],
    }
    stage_cols = {f"{s}_ms": [] for s in STAGES}
    tail: dict[str, list] = {
        "bytes_up": [], "bytes_down": [], "hbm_in_use": [], "hbm_budget": [],
        "flags": [], "regions": [],
    }
    for r in recs:
        cols["seq"].append(r.seq)
        cols["ts"].append(r.ts_ms)
        cols["table_name"].append(r.table)
        cols["trace_id"].append(r.trace_id)
        cols["plan_fp"].append(r.plan_fp)
        cols["strategy"].append(r.strategy)
        cols["build_mode"].append(r.build_mode)
        cols["mesh_devices"].append(r.mesh_devices)
        cols["compile_cache"].append(r.compile_cache)
        cols["ghost"].append("true" if r.ghost else "false")
        for s in STAGES:
            stage_cols[f"{s}_ms"].append(round(r.stage_ms(s), 3))
        tail["bytes_up"].append(r.bytes_up)
        tail["bytes_down"].append(r.bytes_down)
        tail["hbm_in_use"].append(r.hbm_in_use)
        tail["hbm_budget"].append(r.hbm_budget)
        tail["flags"].append(",".join(r.flags))
        tail["regions"].append(_json.dumps([list(x) for x in r.regions]))
    return pa.table({
        "seq": pa.array(cols["seq"], pa.int64()),
        "ts": pa.array(cols["ts"], pa.timestamp("ms")),
        "table_name": pa.array(cols["table_name"], pa.string()),
        "trace_id": pa.array(cols["trace_id"], pa.string()),
        "plan_fp": pa.array(cols["plan_fp"], pa.string()),
        "strategy": pa.array(cols["strategy"], pa.string()),
        "build_mode": pa.array(cols["build_mode"], pa.string()),
        "mesh_devices": pa.array(cols["mesh_devices"], pa.int64()),
        "compile_cache": pa.array(cols["compile_cache"], pa.string()),
        "ghost": pa.array(cols["ghost"], pa.string()),
        **{k: pa.array(v, pa.float64()) for k, v in stage_cols.items()},
        "bytes_up": pa.array(tail["bytes_up"], pa.int64()),
        "bytes_down": pa.array(tail["bytes_down"], pa.int64()),
        "hbm_in_use": pa.array(tail["hbm_in_use"], pa.int64()),
        "hbm_budget": pa.array(tail["hbm_budget"], pa.int64()),
        "flags": pa.array(tail["flags"], pa.string()),
        "regions": pa.array(tail["regions"], pa.string()),
    })


def _device_memory(db) -> pa.Table:
    """information_schema.device_memory: per-device HBM accounting — the
    runtime's own numbers (memory_stats) next to the tile cache's budget
    loop (budget, in-use, headroom, degrade rounds); one shared
    collector (TileCacheManager.device_memory_rows) with /debug/tile."""
    cache = _tile_cache(db)
    return _device_memory_table(
        cache.device_memory_rows() if cache is not None else []
    )


def _device_memory_table(mem_rows: list) -> pa.Table:
    rows = {
        "device": [], "device_kind": [], "bytes_in_use": [], "bytes_limit": [],
        "tile_budget": [], "tile_in_use": [], "tile_headroom": [],
        "chunk_rows": [], "degrade_rounds": [],
    }
    for r in mem_rows:
        for k in rows:
            rows[k].append(r[k])
    return pa.table({
        "device": pa.array(rows["device"], pa.int64()),
        "device_kind": pa.array(rows["device_kind"], pa.string()),
        "bytes_in_use": pa.array(rows["bytes_in_use"], pa.int64()),
        "bytes_limit": pa.array(rows["bytes_limit"], pa.int64()),
        "tile_budget": pa.array(rows["tile_budget"], pa.int64()),
        "tile_in_use": pa.array(rows["tile_in_use"], pa.int64()),
        "tile_headroom": pa.array(rows["tile_headroom"], pa.int64()),
        "chunk_rows": pa.array(rows["chunk_rows"], pa.int64()),
        "degrade_rounds": pa.array(rows["degrade_rounds"], pa.int64()),
    })


def _device_health(db) -> pa.Table:
    """information_schema.device_health: the device supervisor's per-device
    state machine — current state (HEALTHY/SUSPECT/QUARANTINED/PROBING),
    abandonment and quarantine counters, heal history and the last error
    that moved the needle; one shared collector
    (DeviceSupervisor.health_rows) with /debug/tile."""
    from ..utils import device_health

    cache = _tile_cache(db)
    return _device_health_table(device_health.SUPERVISOR.health_rows(
        cache.devices if cache is not None else None
    ))


def _device_health_table(health_rows: list) -> pa.Table:
    rows = {
        "device": [], "device_kind": [], "state": [],
        "consecutive_failures": [], "abandoned_calls": [], "quarantines": [],
        "heals": [], "last_probe_ms": [], "quarantine_age_ms": [],
        "last_error": [],
    }
    for r in health_rows:
        for k in rows:
            rows[k].append(r[k])
    return pa.table({
        "device": pa.array(rows["device"], pa.int64()),
        "device_kind": pa.array(rows["device_kind"], pa.string()),
        "state": pa.array(rows["state"], pa.string()),
        "consecutive_failures": pa.array(
            rows["consecutive_failures"], pa.int64()
        ),
        "abandoned_calls": pa.array(rows["abandoned_calls"], pa.int64()),
        "quarantines": pa.array(rows["quarantines"], pa.int64()),
        "heals": pa.array(rows["heals"], pa.int64()),
        "last_probe_ms": pa.array(rows["last_probe_ms"], pa.int64()),
        "quarantine_age_ms": pa.array(rows["quarantine_age_ms"], pa.int64()),
        "last_error": pa.array(rows["last_error"], pa.string()),
    })


def _region_balance(db) -> pa.Table:
    """information_schema.region_balance: the elastic balancer's live
    view — per-region EWMA load score, its raw inputs (rows/s delta,
    memtable MB, recorder-attributed dispatch ms), hysteresis dwell and
    the table's last enacted decision.  Empty in standalone mode (no
    balancer) and when `balance.enabled` is off (the balancer reads no
    stats, so it has no scores to show)."""
    balancer = getattr(db, "balancer", None)
    return _region_balance_table(balancer.state() if balancer is not None else [])


def _region_balance_table(state_rows: list) -> pa.Table:
    rows = {
        "region_id": [], "table_schema": [], "table_name": [], "node_id": [],
        "score": [], "rows_delta": [], "memtable_mb": [], "dispatch_ms": [],
        "dwell": [], "last_decision": [],
    }
    for r in state_rows:
        rows["region_id"].append(r["region_id"])
        rows["table_schema"].append(r["database"])
        rows["table_name"].append(r["table_name"])
        rows["node_id"].append(r["node_id"])
        rows["score"].append(round(r["score"], 3))
        rows["rows_delta"].append(r["rows_delta"])
        rows["memtable_mb"].append(round(r["memtable_mb"], 3))
        rows["dispatch_ms"].append(round(r["dispatch_ms"], 3))
        rows["dwell"].append(r["dwell"])
        rows["last_decision"].append(r["last_decision"] or "")
    return pa.table({
        "region_id": pa.array(rows["region_id"], pa.int64()),
        "table_schema": pa.array(rows["table_schema"], pa.string()),
        "table_name": pa.array(rows["table_name"], pa.string()),
        "node_id": pa.array(rows["node_id"], pa.int64()),
        "score": pa.array(rows["score"], pa.float64()),
        "rows_delta": pa.array(rows["rows_delta"], pa.int64()),
        "memtable_mb": pa.array(rows["memtable_mb"], pa.float64()),
        "dispatch_ms": pa.array(rows["dispatch_ms"], pa.float64()),
        "dwell": pa.array(rows["dwell"], pa.int64()),
        "last_decision": pa.array(rows["last_decision"], pa.string()),
    })


_TABLES = {
    "tables": _tables,
    "columns": _columns,
    "region_statistics": _region_statistics,
    "region_balance": _region_balance,
    "region_peers": _region_peers,
    "engines": _engines,
    "cluster_info": _cluster_info,
    "process_list": _process_list,
    "schemata": _schemata,
    "partitions": _partitions,
    "flows": _flows,
    "views": _views,
    "tile_cache_entries": _tile_cache_entries,
    "device_dispatches": _device_dispatches,
    "device_memory": _device_memory,
    "device_health": _device_health,
}


# Empty twins of the runtime-introspection tables: schema questions
# (DESCRIBE, planning) read these instead of materializing the dispatch
# ring / walking the tile cache under its lock.  Must construct with the
# exact column set + types of the live builders (the goldens pin both).
_EMPTY_TABLES = {
    "region_balance": lambda: _region_balance_table([]),
    "tile_cache_entries": lambda: _tce_table(_tce_rows()),
    "device_dispatches": lambda: _dispatch_table([]),
    "device_memory": lambda: _device_memory_table([]),
    "device_health": lambda: _device_health_table([]),
}


def table_names() -> list[str]:
    return sorted(_TABLES)
