"""information_schema: queryable system introspection tables.

Role-equivalent of the reference's virtual system schema (reference
catalog/src/system_schema/information_schema/: tables, columns,
region_statistics, cluster_info, engines, procedure_info...): synthesized
from the catalog + storage engine on every scan, so `SELECT * FROM
information_schema.tables` always reflects live state.
"""

from __future__ import annotations

import pyarrow as pa

from ..datatypes.data_type import ConcreteDataType
from ..datatypes.schema import ColumnSchema, Schema, SemanticType

INFORMATION_SCHEMA = "information_schema"


def is_information_schema(database: str) -> bool:
    return database.lower() == INFORMATION_SCHEMA


def build(db, table: str) -> pa.Table:
    fn = _TABLES.get(table.lower())
    if fn is None:
        from ..utils.errors import TableNotFoundError

        raise TableNotFoundError(f"information_schema has no table {table!r}")
    return fn(db)


def schema_of(db, table: str) -> Schema:
    t = build(db, table)
    return Schema(
        columns=[
            ColumnSchema(f.name, ConcreteDataType.from_arrow(f.type), SemanticType.FIELD)
            for f in t.schema
        ]
    )


def _tables(db) -> pa.Table:
    rows = {"table_catalog": [], "table_schema": [], "table_name": [], "table_id": [],
            "table_type": [], "engine": [], "region_count": []}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            rows["table_catalog"].append("greptime")
            rows["table_schema"].append(database)
            rows["table_name"].append(meta.name)
            rows["table_id"].append(meta.table_id)
            rows["table_type"].append("BASE TABLE")
            rows["engine"].append(meta.options.get("engine", "mito"))
            rows["region_count"].append(len(meta.region_ids))
    return pa.table(rows)


def _columns(db) -> pa.Table:
    rows = {"table_schema": [], "table_name": [], "column_name": [], "data_type": [],
            "semantic_type": [], "is_nullable": [], "column_default": []}
    rows["column_key"] = []
    sem_names = {SemanticType.TAG: "TAG", SemanticType.FIELD: "FIELD", SemanticType.TIMESTAMP: "TIMESTAMP"}
    # column_key mirrors the reference's columns view (information_schema
    # columns.rs): PRI for primary-key members, TIME INDEX for the time
    # index, empty for fields
    keys = {SemanticType.TAG: "PRI", SemanticType.TIMESTAMP: "TIME INDEX",
            SemanticType.FIELD: ""}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            for c in meta.schema.columns:
                rows["table_schema"].append(database)
                rows["table_name"].append(meta.name)
                rows["column_name"].append(c.name)
                rows["data_type"].append(c.data_type.value)
                rows["semantic_type"].append(sem_names[c.semantic_type])
                rows["is_nullable"].append("YES" if c.nullable else "NO")
                rows["column_default"].append(str(c.default) if c.default is not None else None)
                rows["column_key"].append(keys[c.semantic_type])
    return pa.table(rows)


def _region_statistics(db) -> pa.Table:
    rows = {"region_id": [], "table_id": [], "region_rows": [], "disk_size": [],
            "memtable_size": [], "sst_num": [], "wal_entry_id": [], "flushed_entry_id": []}
    for stat in db.storage.region_statistics():
        rows["region_id"].append(stat.region_id)
        rows["table_id"].append(stat.region_id // 1024)
        rows["region_rows"].append(stat.num_rows)
        rows["disk_size"].append(stat.sst_bytes)
        rows["memtable_size"].append(stat.memtable_bytes)
        rows["sst_num"].append(stat.sst_count)
        rows["wal_entry_id"].append(stat.wal_entry_id)
        rows["flushed_entry_id"].append(stat.flushed_entry_id)
    return pa.table(rows)


def _engines(db) -> pa.Table:
    return pa.table(
        {
            "engine": ["mito", "metric", "file"],
            "support": ["DEFAULT", "YES", "YES"],
            "comment": [
                "TPU-native LSM time-series engine",
                "logical-table multiplexer over mito",
                "external-file tables",
            ],
        }
    )


def _region_peers(db) -> pa.Table:
    """information_schema.region_peers (reference
    common/catalog information_schema/region_peers.rs): one row per
    region with its hosting peer; standalone hosts everything on peer 0."""
    rows = {"table_catalog": [], "table_schema": [], "table_name": [],
            "region_id": [], "peer_id": [], "peer_addr": [], "is_leader": [],
            "status": []}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            for rid in meta.region_ids:
                rows["table_catalog"].append("greptime")
                rows["table_schema"].append(database)
                rows["table_name"].append(meta.name)
                rows["region_id"].append(rid)
                rows["peer_id"].append(0)
                rows["peer_addr"].append("")
                rows["is_leader"].append("Yes")
                rows["status"].append("ALIVE")
    return pa.table(rows)


def _cluster_info(db) -> pa.Table:
    from .. import __version__

    return pa.table(
        {
            "peer_id": [0],
            "peer_type": ["STANDALONE"],
            "peer_addr": [""],
            "version": [__version__],
            "active_time": [""],
        }
    )


def _schemata(db) -> pa.Table:
    dbs = db.catalog.databases()
    return pa.table(
        {
            "catalog_name": ["greptime"] * len(dbs),
            "schema_name": dbs,
        }
    )


def _partitions(db) -> pa.Table:
    rows = {"table_schema": [], "table_name": [], "partition_name": [], "partition_expression": [],
            "greptime_partition_id": []}
    for database in db.catalog.databases():
        for meta in db.catalog.tables(database):
            rule = meta.partition_rule.to_dict()
            for i, rid in enumerate(meta.region_ids):
                rows["table_schema"].append(database)
                rows["table_name"].append(meta.name)
                rows["partition_name"].append(f"p{i}")
                rows["partition_expression"].append(str(rule))
                rows["greptime_partition_id"].append(rid)
    return pa.table(rows)


def _flows(db) -> pa.Table:
    """information_schema.flows (reference
    catalog/src/system_schema/information_schema/flows.rs)."""
    infos = db.flows.list_flows() if hasattr(db, "flows") else []
    return pa.table(
        {
            "flow_name": [i.name for i in infos],
            "flow_id": [i.flow_id for i in infos],
            "state_size": [0 for _ in infos],
            "table_catalog": ["greptime" for _ in infos],
            "flow_definition": [i.sql for i in infos],
            "comment": [i.comment or "" for i in infos],
            "expire_after": [i.expire_after_ms for i in infos],
            "source_table_names": [i.source_table for i in infos],
            "sink_table_name": [i.sink_table for i in infos],
            "options": [i.mode for i in infos],
        }
    )


def _views(db) -> pa.Table:
    """information_schema.views (reference
    catalog/src/system_schema/information_schema/views.rs)."""
    rows = {"table_catalog": [], "table_schema": [], "table_name": [], "view_definition": []}
    for database in db.catalog.databases():
        for name, sql_text in sorted(db.catalog.views(database).items()):
            rows["table_catalog"].append("greptime")
            rows["table_schema"].append(database)
            rows["table_name"].append(name)
            rows["view_definition"].append(sql_text)
    return pa.table(
        {k: pa.array(v, pa.string()) for k, v in rows.items()}
    )


def _process_list(db) -> pa.Table:
    """information_schema.process_list (reference
    catalog/src/system_schema/information_schema/process_list.rs)."""
    procs = db.process_manager.list() if hasattr(db, "process_manager") else []
    addr = db.process_manager.server_addr if procs else "standalone"
    return pa.table(
        {
            "id": pa.array([f"{addr}/{p.process_id}" for p in procs], pa.string()),
            "catalog": pa.array(["greptime" for _ in procs], pa.string()),
            "schemas": pa.array([p.database for p in procs], pa.string()),
            "query": pa.array([p.query for p in procs], pa.string()),
            "client": pa.array([p.client for p in procs], pa.string()),
            "frontend": pa.array([addr for _ in procs], pa.string()),
            "start_timestamp": pa.array(
                [p.start_time_ms for p in procs], pa.timestamp("ms")
            ),
            "elapsed_time": pa.array([p.elapsed_ms() for p in procs], pa.int64()),
        }
    )


_TABLES = {
    "tables": _tables,
    "columns": _columns,
    "region_statistics": _region_statistics,
    "region_peers": _region_peers,
    "engines": _engines,
    "cluster_info": _cluster_info,
    "process_list": _process_list,
    "schemata": _schemata,
    "partitions": _partitions,
    "flows": _flows,
    "views": _views,
}


def table_names() -> list[str]:
    return sorted(_TABLES)
