"""Region partition rules: route rows to regions.

Role-equivalent of the reference's expression-based partitioning
(reference partition/src/multi_dim.rs `MultiDimPartitionRule`,
manager.rs:192 `split_rows`): a table's rows are split across regions by a
rule evaluated per row.  We provide three rules:

  SingleRegionRule  — everything in one region (default, like an
                      unpartitioned reference table)
  HashPartitionRule — hash(tag columns) % n, the common TSBS layout
  RangePartitionRule— ordered ranges over one column's values, the
                      reference's PARTITION ON COLUMNS surface
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


@dataclass
class RegionRoute:
    """One region's placement: the leader datanode that serves writes plus
    optional read-only follower replicas (reference
    partition/src/manager.rs RegionRoute with leader_peer + follower_peers).

    The wire/KV form stays backward compatible: a bare int is a route with
    no followers (what every pre-replica KV holds), a dict carries both.
    """

    leader: int
    followers: list[int] = field(default_factory=list)

    def to_wire(self):
        if not self.followers:
            return self.leader
        return {"leader": self.leader, "followers": list(self.followers)}

    @staticmethod
    def from_wire(v) -> "RegionRoute":
        if isinstance(v, dict):
            return RegionRoute(int(v["leader"]), [int(f) for f in v.get("followers", [])])
        return RegionRoute(int(v))


class PartitionRule:
    def num_partitions(self) -> int:
        raise NotImplementedError

    def partition_indices(self, table: pa.Table) -> np.ndarray:
        """Per-row partition index [0, num_partitions)."""
        raise NotImplementedError

    def split(self, table: pa.Table) -> list[pa.Table]:
        """Split rows into per-partition tables (reference split_rows):
        ONE compute pass for the indices, ONE stable-ordered `take`, then
        zero-copy slices — instead of one filter mask per partition.
        Row order within each partition is preserved (stable argsort), so
        last-write-wins append order survives routing."""
        n = self.num_partitions()
        if n == 1 or table.num_rows == 0:
            return [table] + [table.schema.empty_table() for _ in range(n - 1)]
        idx = self.partition_indices(table)
        counts = np.bincount(idx, minlength=n)
        empty = table.schema.empty_table()
        hot = int(counts.argmax())
        if counts[hot] == table.num_rows:
            # all rows in one partition (the bulk-ingest common case):
            # skip the take copy entirely
            out = [empty] * n
            out[hot] = table
            return out
        order = np.argsort(idx, kind="stable")
        taken = table.take(pa.array(order))
        offsets = np.concatenate(([0], np.cumsum(counts)))
        return [
            taken.slice(int(offsets[p]), int(counts[p])) if counts[p] else empty
            for p in range(n)
        ]

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "PartitionRule":
        kind = d["kind"]
        if kind == "single":
            return SingleRegionRule()
        if kind == "hash":
            return HashPartitionRule(d["columns"], d["n"])
        if kind == "range":
            return RangePartitionRule(d["column"], d["bounds"])
        if kind == "multi_dim":
            return MultiDimPartitionRule(d["columns"], d["exprs"])
        raise ValueError(f"unknown partition rule kind: {kind}")


@dataclass
class SingleRegionRule(PartitionRule):
    def num_partitions(self) -> int:
        return 1

    def partition_indices(self, table: pa.Table) -> np.ndarray:
        return np.zeros(table.num_rows, dtype=np.int32)

    def to_dict(self) -> dict:
        return {"kind": "single"}


@dataclass
class HashPartitionRule(PartitionRule):
    columns: list[str]
    n: int

    def num_partitions(self) -> int:
        return self.n

    def partition_indices(self, table: pa.Table) -> np.ndarray:
        h = np.zeros(table.num_rows, dtype=np.uint64)
        for c in self.columns:
            col = table[c]
            if pa.types.is_dictionary(col.type):
                col = pc.cast(col, col.type.value_type)
            if isinstance(col, pa.ChunkedArray):
                col = col.combine_chunks()
            # crc32 per DISTINCT value (dictionary-encode in C++), gathered
            # back via one vectorized take — stable across processes
            # (unlike Python hash()) and identical to the per-row loop.
            enc = pc.dictionary_encode(col)
            salts = np.array(
                [zlib.crc32(repr(v).encode()) for v in enc.dictionary.to_pylist()]
                or [0],
                dtype=np.uint64,
            )
            idxs = np.asarray(pc.fill_null(enc.indices, -1), dtype=np.int64)
            hc = np.where(
                idxs >= 0,
                salts[np.clip(idxs, 0, len(salts) - 1)],
                np.uint64(zlib.crc32(repr(None).encode())),
            )
            h = h * np.uint64(1000003) + hc
        return (h % np.uint64(self.n)).astype(np.int32)

    def to_dict(self) -> dict:
        return {"kind": "hash", "columns": self.columns, "n": self.n}


@dataclass
class MultiDimPartitionRule(PartitionRule):
    """Expression-based multi-dimensional partitioning (reference
    partition/src/multi_dim.rs:50 `MultiDimPartitionRule`, RFC
    2024-02-21-multi-dimension-partition-rule): one boolean expression per
    region, evaluated per row; first matching region wins.

    Expressions persist as SQL text (re-parsed lazily) so the rule
    round-trips through the JSON catalog like the other rules.  A row that
    matches no expression is a rule-completeness violation and raises —
    the reference's checker.rs rejects incomplete rules at CREATE; we
    enforce at write time as the backstop."""

    columns: list[str]
    exprs: list[str]  # SQL boolean expressions, one per region

    def __post_init__(self):
        self._parsed = None

    def _compiled(self):
        if self._parsed is None:
            from ..query.sql_parser import Parser

            self._parsed = [Parser(e).parse_expr() for e in self.exprs]
        return self._parsed

    def num_partitions(self) -> int:
        return len(self.exprs)

    def partition_indices(self, table: pa.Table) -> np.ndarray:
        from ..query.cpu_exec import eval_expr

        n = table.num_rows
        out = np.full(n, -1, dtype=np.int32)
        unassigned = np.ones(n, dtype=bool)
        for p, expr in enumerate(self._compiled()):
            m = eval_expr(expr, table)
            if isinstance(m, pa.Scalar):
                mask = np.full(n, bool(m.as_py()))
            else:
                mask = np.asarray(pc.fill_null(m, False))
            hit = unassigned & mask
            out[hit] = p
            unassigned &= ~mask
            if not unassigned.any():
                break
        if unassigned.any():
            i = int(np.flatnonzero(unassigned)[0])
            row = {c: table[c][i].as_py() for c in self.columns if c in table.column_names}
            raise ValueError(
                f"row {row} matches no partition expression (incomplete rule)"
            )
        return out

    def to_dict(self) -> dict:
        return {"kind": "multi_dim", "columns": self.columns, "exprs": self.exprs}


@dataclass
class RangePartitionRule(PartitionRule):
    """Ranges over one column: bounds [b0, b1, ...] define len(bounds)+1
    partitions: (-inf, b0), [b0, b1), ..., [bn, +inf)."""

    column: str
    bounds: list = field(default_factory=list)

    def num_partitions(self) -> int:
        return len(self.bounds) + 1

    def partition_indices(self, table: pa.Table) -> np.ndarray:
        n = table.num_rows
        if not self.bounds:
            return np.zeros(n, dtype=np.int32)
        # Sorted bounds (the only shape CREATE emits): the break-at-first-
        # failing-bound count equals the total >=-count, which vectorizes
        # to one compute pass per bound (nulls compare null -> False -> 0,
        # matching the scalar loop's None handling).
        try:
            ascending = all(
                self.bounds[i] <= self.bounds[i + 1]
                for i in range(len(self.bounds) - 1)
            )
        except TypeError:
            ascending = False
        if ascending:
            try:
                out = np.zeros(n, dtype=np.int32)
                col = table[self.column]
                for b in self.bounds:
                    ge = pc.fill_null(pc.greater_equal(col, pa.scalar(b)), False)
                    if isinstance(ge, pa.ChunkedArray):
                        ge = ge.combine_chunks()
                    out += np.asarray(ge, dtype=np.int32)
                return out
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError, pa.ArrowTypeError):
                pass  # mixed-type bounds: scalar loop below decides
        vals = table[self.column].to_pylist()
        out = np.empty(n, dtype=np.int32)
        for i, v in enumerate(vals):
            p = 0
            for b in self.bounds:
                if v is not None and v >= b:
                    p += 1
                else:
                    break
            out[i] = p
        return out

    def to_dict(self) -> dict:
        return {"kind": "range", "column": self.column, "bounds": self.bounds}
