"""Query process tracking and cancellation.

Role-equivalent of the reference's `ProcessManager`
(reference catalog/src/process_manager.rs:43): every running query is
registered with an id, query text, and start time; `information_schema.
process_list` exposes them; `KILL <id>` flags the process, and the scan
loop raises `QueryCancelledError` at its next cancellation point.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..utils.errors import GreptimeError, InvalidArgumentsError, StatusCode


class QueryCancelledError(GreptimeError):
    code = StatusCode.CANCELLED


@dataclass
class Process:
    process_id: int
    database: str
    query: str
    start_time_ms: int
    client: str = "local"
    cancelled: threading.Event = field(default_factory=threading.Event)

    def elapsed_ms(self, now: float | None = None) -> int:
        return int((now or time.time()) * 1000) - self.start_time_ms


class ProcessManager:
    """Thread-safe registry of in-flight queries (one per execute call)."""

    def __init__(self, server_addr: str = "standalone"):
        self.server_addr = server_addr
        self._lock = threading.Lock()
        self._next_id = 1
        self._processes: dict[int, Process] = {}
        # the process currently executing on THIS thread (cancellation point
        # checks consult it without plumbing tickets through every layer)
        self._current = threading.local()

    def register(self, database: str, query: str, client: str = "local") -> Process:
        with self._lock:
            pid = self._next_id
            self._next_id += 1
            proc = Process(
                process_id=pid,
                database=database,
                query=query,
                start_time_ms=int(time.time() * 1000),
                client=client,
            )
            self._processes[pid] = proc
        self._current.proc = proc
        return proc

    def deregister(self, proc: Process):
        with self._lock:
            self._processes.pop(proc.process_id, None)
        if getattr(self._current, "proc", None) is proc:
            self._current.proc = None

    def list(self) -> list[Process]:
        with self._lock:
            return sorted(self._processes.values(), key=lambda p: p.process_id)

    def kill(self, process_id: int) -> bool:
        """Flag a process for cancellation (reference KILL <process_id>)."""
        with self._lock:
            proc = self._processes.get(process_id)
        if proc is None:
            raise InvalidArgumentsError(f"no running query with id {process_id}")
        proc.cancelled.set()
        return True

    def check_cancelled(self):
        """Cancellation point: raise if this thread's query was killed."""
        proc = getattr(self._current, "proc", None)
        if proc is not None and proc.cancelled.is_set():
            raise QueryCancelledError(
                f"query {proc.process_id} cancelled by KILL"
            )

    class _Ticket:
        def __init__(self, mgr: "ProcessManager", proc: Process):
            self.mgr, self.proc = mgr, proc

        def __enter__(self):
            return self.proc

        def __exit__(self, *exc):
            self.mgr.deregister(self.proc)
            return False

    def track(self, database: str, query: str, client: str = "local") -> "ProcessManager._Ticket":
        return self._Ticket(self, self.register(database, query, client))
