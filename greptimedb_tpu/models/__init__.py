from .catalog import Catalog, TableMeta
from .partition import PartitionRule, HashPartitionRule, RangePartitionRule, SingleRegionRule

__all__ = [
    "Catalog",
    "TableMeta",
    "PartitionRule",
    "HashPartitionRule",
    "RangePartitionRule",
    "SingleRegionRule",
]
