"""Catalog: databases -> tables -> regions, persisted in a KV-style store.

Role-equivalent of the reference's catalog + table metadata plane
(reference catalog/src/kvbackend/, common/meta/src/key.rs:389
`TableMetadataManager`): table ids are allocated from a sequence, table
metadata (schema, partition rule, region ids) lives in a JSON KV file, and
region ids are derived as table_id * MAX_REGIONS + seq (matching the
reference's RegionId = (table_id << 32) | region_seq encoding).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field

from ..datatypes.schema import Schema
from ..utils.errors import (
    DatabaseNotFoundError,
    TableAlreadyExistsError,
    TableNotFoundError,
)
from .partition import PartitionRule, SingleRegionRule

MAX_REGIONS_PER_TABLE = 1 << 10
DEFAULT_CATALOG = "greptime"
DEFAULT_SCHEMA = "public"


def region_id(table_id: int, seq: int) -> int:
    return table_id * MAX_REGIONS_PER_TABLE + seq


@dataclass
class TableMeta:
    table_id: int
    name: str
    database: str
    schema: Schema
    partition_rule: PartitionRule = field(default_factory=SingleRegionRule)
    options: dict = field(default_factory=dict)
    # Region-id generation offset: repartition allocates the new partition
    # set at a fresh base so old and staging region ids never collide
    # (reference repartition RFC's staging regions).
    region_id_base: int = 0

    @property
    def region_ids(self) -> list[int]:
        return [
            region_id(self.table_id, self.region_id_base + i)
            for i in range(self.partition_rule.num_partitions())
        ]

    def to_dict(self) -> dict:
        return {
            "table_id": self.table_id,
            "name": self.name,
            "database": self.database,
            "schema": self.schema.to_json(),
            "partition_rule": self.partition_rule.to_dict(),
            "options": self.options,
            "region_id_base": self.region_id_base,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TableMeta":
        return cls(
            table_id=d["table_id"],
            name=d["name"],
            database=d["database"],
            schema=Schema.from_json(d["schema"]),
            partition_rule=PartitionRule.from_dict(d["partition_rule"]),
            options=d.get("options", {}),
            region_id_base=d.get("region_id_base", 0),
        )


class Catalog:
    """In-process catalog with optional file persistence.

    With `path=None` it is the reference's memory catalog (tests); with a
    path it journals every mutation, the reference's KV-backed catalog.
    """

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        self._databases: dict[str, dict[str, TableMeta]] = {DEFAULT_SCHEMA: {}}
        self._views: dict[str, dict[str, str]] = {}  # db -> name -> SQL text
        self._next_table_id = 1024  # reference reserves low ids for system tables
        # Bumped on every mutation — plan caches key on it so DDL invalidates
        # cached plans (the reference invalidates via KV cache broadcasts).
        self.revision = 0
        self._loaded_stat: tuple | None = None  # (mtime_ns, size) at last load
        if path and os.path.exists(path):
            self._load()

    # ---- databases --------------------------------------------------------
    def create_database(self, name: str, if_not_exists: bool = False):
        with self._ddl_guard():
            if name in self._databases:
                if if_not_exists:
                    return
                raise TableAlreadyExistsError(f"database {name!r} already exists")
            self._databases[name] = {}
            self._persist()

    def drop_database(self, name: str):
        with self._ddl_guard():
            if name not in self._databases:
                raise DatabaseNotFoundError(f"database not found: {name}")
            if name == DEFAULT_SCHEMA:
                raise DatabaseNotFoundError("cannot drop the default database")
            del self._databases[name]
            self._views.pop(name, None)
            self._persist()

    def databases(self) -> list[str]:
        with self._lock:
            return sorted(self._databases)

    def reload(self):
        """Re-read the persisted catalog: multi-process deployments (a
        distributed frontend beside other frontends/standalone tools on
        the same shared storage) see each other's DDL this way — the
        file plays the role of the reference's KV + cache invalidation."""
        with self._lock:
            if self.path and os.path.exists(self.path):
                # unchanged file = no-op: reload is called on every SHOW
                # by multi-process frontends, and an unconditional bump
                # would evict warm plan caches for nothing
                st = os.stat(self.path)
                if self._loaded_stat == (st.st_mtime_ns, st.st_size):
                    return
                self._load()
                self.revision += 1  # invalidate plan caches keyed on it

    def _ddl_guard(self):
        """Cross-PROCESS DDL critical section: an exclusive flock around
        reload -> mutate -> persist.  Without it two frontends over one
        shared catalog file race read-modify-write: both allocate the
        same table_id and the second _persist() erases the first's table
        while its regions stay open (the reference serializes DDL through
        metasrv procedures + KV transactions; the lock file plays the KV
        txn's role here).  In-memory-only catalogs (tests) skip it."""
        from contextlib import contextmanager

        @contextmanager
        def guard():
            with self._lock:
                if not self.path:
                    yield
                    return
                import fcntl

                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                with open(self.path + ".lock", "a") as lf:
                    fcntl.flock(lf, fcntl.LOCK_EX)
                    try:
                        if os.path.exists(self.path):
                            st = os.stat(self.path)
                            if self._loaded_stat != (st.st_mtime_ns, st.st_size):
                                self._load()  # another process mutated it
                                self.revision += 1
                        yield
                    finally:
                        fcntl.flock(lf, fcntl.LOCK_UN)

        return guard()

    # ---- tables -----------------------------------------------------------
    def allocate_table_id(self) -> int:
        """Burn a table id WITHOUT publishing a table: the durable
        CreateTable procedure allocates first, creates regions, then
        commits metadata (reference TableMetadataAllocator,
        common/meta/src/ddl/table_meta.rs) — a crash between steps wastes
        the id but can never collide."""
        with self._ddl_guard():
            tid = self._next_table_id
            self._next_table_id += 1
            self._persist()
            return tid

    def create_table(
        self,
        name: str,
        schema: Schema,
        partition_rule: PartitionRule | None = None,
        database: str = DEFAULT_SCHEMA,
        if_not_exists: bool = False,
        options: dict | None = None,
        on_create=None,
        table_id: int | None = None,
    ) -> TableMeta:
        """Create a table. `on_create(meta)` runs under the catalog lock
        before the table becomes visible, so callers can create storage
        regions atomically with the metadata publish (the reference commits
        region creation and KV metadata in one DDL procedure step,
        common/meta/src/ddl/create_table.rs).  `table_id` commits a
        previously `allocate_table_id`-reserved id (procedure path)."""
        with self._ddl_guard():
            db = self._db(database)
            if name in db:
                if if_not_exists:
                    return db[name]
                raise TableAlreadyExistsError(f"table {name!r} already exists")
            meta = TableMeta(
                table_id=table_id if table_id is not None else self._next_table_id,
                name=name,
                database=database,
                schema=schema,
                partition_rule=partition_rule or SingleRegionRule(),
                options=options or {},
            )
            if table_id is None:
                self._next_table_id += 1
            else:
                self._next_table_id = max(self._next_table_id, table_id + 1)
            if on_create is not None:
                on_create(meta)
            db[name] = meta
            self._persist()
            return meta

    def drop_table(self, name: str, database: str = DEFAULT_SCHEMA) -> TableMeta:
        with self._ddl_guard():
            db = self._db(database)
            if name not in db:
                raise TableNotFoundError(f"table not found: {name}")
            meta = db.pop(name)
            self._persist()
            return meta

    def rename_table(
        self, old: str, new: str, database: str = DEFAULT_SCHEMA
    ) -> TableMeta:
        """Rename keeps table_id and regions (the reference's RenameTable
        alter kind rewrites only the name keys, common/meta/src/key/table_name.rs)."""
        with self._ddl_guard():
            db = self._db(database)
            if old not in db:
                raise TableNotFoundError(f"table not found: {database}.{old}")
            if new in db:
                raise TableAlreadyExistsError(f"table {new!r} already exists")
            meta = db.pop(old)
            meta.name = new
            db[new] = meta
            self._persist()
            return meta

    def table(self, name: str, database: str = DEFAULT_SCHEMA) -> TableMeta:
        with self._lock:
            db = self._db(database)
            if name not in db:
                raise TableNotFoundError(f"table not found: {database}.{name}")
            return db[name]

    def has_table(self, name: str, database: str = DEFAULT_SCHEMA) -> bool:
        with self._lock:
            return name in self._databases.get(database, {})

    def tables(self, database: str = DEFAULT_SCHEMA) -> list[TableMeta]:
        with self._lock:
            return sorted(self._db(database).values(), key=lambda m: m.name)

    def update_table(self, meta: TableMeta):
        with self._ddl_guard():
            self._db(meta.database)[meta.name] = meta
            self._persist()

    # ---- views -------------------------------------------------------------
    # Views are stored as their defining SQL text and re-planned at query
    # time (the reference stores view_info in KV and decodes the logical
    # plan, common/meta/src/ddl/create_view.rs + key/view_info.rs).
    def create_view(
        self,
        name: str,
        sql_text: str,
        database: str = DEFAULT_SCHEMA,
        or_replace: bool = False,
        if_not_exists: bool = False,
    ):
        with self._ddl_guard():
            self._db(database)  # validates the database exists
            views = self._views.setdefault(database, {})
            if name in views and not or_replace:
                if if_not_exists:
                    return
                raise TableAlreadyExistsError(f"view {name!r} already exists")
            if self.has_table(name, database):
                raise TableAlreadyExistsError(f"table {name!r} already exists")
            views[name] = sql_text
            self._persist()

    def drop_view(self, name: str, database: str = DEFAULT_SCHEMA, if_exists: bool = False):
        with self._ddl_guard():
            views = self._views.get(database, {})
            if name not in views:
                if if_exists:
                    return
                raise TableNotFoundError(f"view not found: {database}.{name}")
            del views[name]
            self._persist()

    def view(self, name: str, database: str = DEFAULT_SCHEMA) -> str | None:
        with self._lock:
            return self._views.get(database, {}).get(name)

    def views(self, database: str = DEFAULT_SCHEMA) -> dict[str, str]:
        with self._lock:
            return dict(self._views.get(database, {}))

    # ---- persistence ------------------------------------------------------
    def _db(self, database: str) -> dict[str, TableMeta]:
        if database not in self._databases:
            raise DatabaseNotFoundError(f"database not found: {database}")
        return self._databases[database]

    def _persist(self):
        self.revision += 1
        if not self.path:
            return
        state = {
            "next_table_id": self._next_table_id,
            "databases": {
                db: {name: meta.to_dict() for name, meta in tables.items()}
                for db, tables in self._databases.items()
            },
            "views": self._views,
        }
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        st = os.stat(self.path)
        self._loaded_stat = (st.st_mtime_ns, st.st_size)  # disk == memory

    def _load(self):
        st = os.stat(self.path)
        self._loaded_stat = (st.st_mtime_ns, st.st_size)
        with open(self.path) as f:
            state = json.load(f)
        self._next_table_id = state["next_table_id"]
        self._databases = {
            db: {name: TableMeta.from_dict(d) for name, d in tables.items()}
            for db, tables in state["databases"].items()
        }
        self._views = state.get("views", {})
        if DEFAULT_SCHEMA not in self._databases:
            self._databases[DEFAULT_SCHEMA] = {}
