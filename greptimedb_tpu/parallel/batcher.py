"""Cross-query device batching + windowed result cache.

The per-dispatch device->host round-trip (~100 ms on a remote-device
tunnel) dwarfs the warm compute (1-4 ms), so at dashboard-fleet QPS the
LINK, not the chip, is the bottleneck.  Admission coalescing (`
admission.coalesce`) already merges bit-identical concurrent plans onto
one dispatch; this module extends the same contract to DISTINCT plans:

  * `QueryBatcher` — warm queries against the same table that arrive
    within `batch.window_ms` of each other form a batch.  The first
    arrival is the LEADER: it waits out the window, then executes every
    member's dispatch back-to-back on the device stream in *deferred-
    fetch* mode (the executor returns a `PendingFetch` instead of
    fetching), flattens every member's packed output leaves and brings
    them home in ONE `jax.device_get` — one tunnel round-trip amortized
    across the whole batch — then runs each member's decode
    continuation host-side.  Members share the READBACK, never each
    other's math: each ran its own compiled program over its own plan,
    so results are bit-identical to solo runs by construction.  Any
    member that cannot be packed (dispatch error, decode verdict such
    as a hash-slot overflow, an injected `batch.pack` fault) degrades
    to its own solo dispatch on its own thread — batching can delay a
    query, never wrong it.  `batch.window_ms = 0` (the default)
    disables the layer entirely: today's path bit-for-bit.

  * `WindowedResultCache` — finished executor results keyed on
    (literal-insensitive plan fingerprint, filter-literal digest,
    bucket-aligned time window, per-region manifest version + WAL tail
    id).  A sliding dashboard that re-asks for the same aligned window
    re-serves with ZERO dispatch; any write moves the WAL tail and any
    flush/compaction bumps the manifest version, so stale entries are
    simply never reachable — the key IS the invalidation rule.  The
    snapshot versions are read BEFORE the query executes, so a write
    landing mid-query can only strand an unreachable old-versions
    entry, never publish a newer result under an older snapshot key.
    LRU-bounded by `batch.result_cache_mb` (0 = off).

  * **Mega-program fusion** (`batch.fuse_programs`, default ON) — the
    leader goes one step further than the shared readback: each member's
    dispatch is CAPTURED at the executor's dispatch site (lowered plan,
    device-resident sources, dynamic traced inputs, decode continuation)
    instead of executed, and the whole tick compiles into ONE fused XLA
    program that replays every member's fold op-for-op as independent
    branches over the shared resident planes — one XLA invocation per
    batch tick, not per member, so the chip rather than the dispatch
    loop sets the ceiling.  The fused program is keyed on the multiset
    of the members' literal-insensitive program keys (plan structure +
    shape buckets; literals, grids and time bounds ride as dynamic
    traced inputs, PR 13-style), so a dashboard fleet sliding its
    windows re-hits the fused compile cache with zero recompiles.  Any
    capture, trace, compile, or dispatch failure — including a
    multi-member HBM exhaustion, which must retry at per-member
    granularity to shrink — degrades to the per-member packed path
    above (`greptime_batch_fuse_degraded_total`); a member the capture
    cannot reach (host/cold/streamed serves) is answered by the
    per-member path in the same tick (partial fusion).

Fault points: `batch.pack` fires immediately before the mega-readback;
`batch.result_cache` fires on every cache get/put; `batch.fuse` fires
before each member's capture (op="capture") and before the fused
dispatch (op="fuse").  All degrade, never corrupt: a pack failure solos
every member, a cache failure is a miss, a fuse failure re-runs the
tick through the per-member path.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict

import jax

from ..utils import device_health, flight_recorder, metrics, rtt_sim, tracing
from ..utils.deadline import check_deadline, current_deadline
from ..utils.fault_injection import fire as _fault_fire

# ---- deferred device->host fetches -----------------------------------------
# Thread-local flag the batch leader raises around each member's dispatch:
# the executor's _finalize sees it and returns a PendingFetch (dispatched,
# unfetched) instead of paying a per-member device_get.

_DEFER = threading.local()


def defer_active() -> bool:
    return getattr(_DEFER, "active", False)


@contextlib.contextmanager
def defer_fetch():
    prev = getattr(_DEFER, "active", False)
    _DEFER.active = True
    try:
        yield
    finally:
        _DEFER.active = prev


@contextlib.contextmanager
def defer_suppressed():
    """Force eager fetches inside a deferred scope.  The region-streamed
    path releases each region's planes right after folding its partials,
    so its intermediate fetches must complete while the planes are
    guaranteed alive — it never defers."""
    prev = getattr(_DEFER, "active", False)
    _DEFER.active = False
    try:
        yield
    finally:
        _DEFER.active = prev


# ---- mega-fusion dispatch capture -------------------------------------------
# Thread-local flag the batch leader raises around each member's execute:
# the executor's dispatch site sees it and returns a CapturedDispatch
# (everything the fused program needs, nothing executed) instead of
# dispatching.  Serve paths that answer BEFORE the dispatch site (host
# fast path, cold consolidation, streamed spill) return their final
# result straight through the capture — those members simply aren't
# fusable this tick and the per-member path owns them.

_CAPTURE = threading.local()


def capture_active() -> bool:
    return getattr(_CAPTURE, "active", False)


@contextlib.contextmanager
def capture_dispatch():
    prev = getattr(_CAPTURE, "active", False)
    _CAPTURE.active = True
    try:
        yield
    finally:
        _CAPTURE.active = prev


class CapturedDispatch:
    """One member's dispatch-ready state, captured instead of executed.

    `key` is the member's `_tile_program` cache key (plan, nullable
    count-cols, finalize spec) — literal-insensitive by the dynamic-spec
    contract, so the multiset of member keys IS the fused program's
    compile key.  `sources`/`dyn` are the device-resident source planes
    and the dynamic traced inputs for this specific tick.  `finish` is
    the decode continuation (host-fetched leaves in, decoded pa.Table or
    a rerun-verdict None out — same contract as `PendingFetch.finish`).
    Only the FIRST attempts-ladder rung is captured: a rerun verdict in
    the fused leaves degrades the member to a solo run that walks the
    full ladder."""

    __slots__ = ("key", "sources", "dyn", "finish")

    def __init__(self, key, sources, dyn, finish):
        self.key = key
        self.sources = sources
        self.dyn = dyn
        self.finish = finish


class PendingFetch:
    """One query's dispatched-but-unfetched packed device result: the
    output leaves still on device plus the decode continuation.  `finish`
    takes the host-fetched leaves (same order as `leaves`) and returns
    the decoded pa.Table — or None for a rerun verdict (hash-slot
    overflow / limb quantization bound), which the batcher turns into a
    solo degrade."""

    __slots__ = ("leaves", "finish")

    def __init__(self, leaves, finish):
        self.leaves = list(leaves)
        self.finish = finish


# ---- windowed result cache --------------------------------------------------


class WindowedResultCache:
    """LRU byte-bounded memo of finished executor results.

    Values are (pa.Table, post_done) — both immutable, so a hit hands
    back the stored objects directly.  `post_done` rides along because a
    device-finalized result already consumed some post-ops; the host
    replay must skip exactly those on a hit too, or the hit would
    double-apply LIMIT/HAVING."""

    # per-entry bookkeeping floor: a tiny table still costs key storage
    _ENTRY_OVERHEAD = 1 << 10

    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # key -> (table, post_done, nbytes)
        self._used = 0

    @staticmethod
    def key_for(executor, lowering, schema, ctx):
        """Cache key for one query, or None when not fingerprintable.

        (plan_fp, literals, window, versions): `plan_fp` is the literal-
        insensitive family fingerprint (filter STRUCTURE, bucket
        geometry); `literals` digests the filter values it elides;
        `window` is the effective scan time range, expressed in bucket
        units when both bounds sit exactly on the query's bucket grid
        (the canonical form a refreshing dashboard re-hits) and verbatim
        otherwise — both forms are exact, never merging windows that
        could select different rows; `versions` pins the data snapshot
        exactly like coalescing's `_family_key` does."""
        plan_fp = executor._plan_fp(lowering, ctx)
        if plan_fp is None:
            return None
        try:
            versions = tuple(
                (
                    r.region_id,
                    r.manifest_mgr.manifest.manifest_version,
                    r.wal.last_entry_id,
                )
                for r in ctx.regions
            )
            literals = repr(tuple(lowering.scan.filters))
            window = WindowedResultCache._window_key(lowering, schema)
        except Exception:  # noqa: BLE001 — fingerprinting is best-effort
            return None
        return (plan_fp, literals, window, versions)

    @staticmethod
    def _window_key(lowering, schema):
        tr = getattr(lowering.scan, "time_range", None)
        if tr is None:
            return ("full",)
        lo, hi = int(tr[0]), int(tr[1])
        bucket = getattr(lowering, "bucket", None)
        if bucket is not None and lo > -(1 << 61) and hi < (1 << 61):
            try:
                _ts, interval_ms, origin = bucket
                # same ms->native conversion as the plan's bucket geometry
                unit_ns = schema.time_index.data_type.timestamp_unit_ns()
                step = max(int(interval_ms * 1_000_000) // max(unit_ns, 1), 1)
                if (lo - origin) % step == 0 and (hi - origin) % step == 0:
                    # bijective given the plan: interval + origin are
                    # structural and already inside plan_fp
                    return ("aligned", (lo - origin) // step, (hi - origin) // step)
            except Exception:  # noqa: BLE001 — fall back to the verbatim form
                pass
        return ("raw", lo, hi)

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            return entry[0], entry[1]

    def put(self, key, table, post_done):
        try:
            nbytes = int(table.nbytes) + self._ENTRY_OVERHEAD
        except Exception:  # noqa: BLE001 — unsized results are uncacheable
            return
        if nbytes > self.budget:
            return
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._used -= old[2]
            self._entries[key] = (table, frozenset(post_done or ()), nbytes)
            self._used += nbytes
            while self._used > self.budget and self._entries:
                _, dropped = self._entries.popitem(last=False)
                self._used -= dropped[2]
                evicted += 1
        if evicted:
            metrics.QUERY_BATCH_RESULT_CACHE_EVICTIONS_TOTAL.inc(evicted)

    def purge_region(self, region_id: int):
        """Proactive drop of every entry touching the region.  The
        version-carrying key already makes stale entries unreachable;
        purging just returns their bytes to the budget immediately."""
        evicted = 0
        with self._lock:
            for key in list(self._entries):
                versions = key[3]
                if any(v[0] == region_id for v in versions):
                    self._used -= self._entries.pop(key)[2]
                    evicted += 1
        if evicted:
            metrics.QUERY_BATCH_RESULT_CACHE_EVICTIONS_TOTAL.inc(evicted)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._used}


# ---- the query batcher ------------------------------------------------------


class _Member:
    __slots__ = (
        "lowering", "schema", "time_bounds", "ctx",
        "event", "result", "post_done", "solo", "served",
    )

    def __init__(self, lowering, schema, time_bounds, ctx):
        self.lowering = lowering
        self.schema = schema
        self.time_bounds = time_bounds
        self.ctx = ctx
        self.event = threading.Event()
        self.result = None
        self.post_done = frozenset()
        self.solo = False  # degrade: owner thread runs its own solo dispatch
        self.served = False  # result/post_done came from the batch


class _Batch:
    __slots__ = ("members", "closed")

    def __init__(self):
        self.members: list[_Member] = []
        self.closed = False


class QueryBatcher:
    """Forms per-table batches of warm queries and runs each batch as
    back-to-back async dispatches sharing ONE packed readback.  The
    executor calls `submit` only for warm, fingerprintable families with
    `batch.window_ms > 0`; everything else takes the existing path."""

    # sanity ceiling on the leader's window sleep, whatever the knob says
    _WINDOW_CAP_S = 0.25

    def __init__(self, executor):
        self._ex = executor
        self._lock = threading.Lock()
        self._open: dict[str, _Batch] = {}  # table_key -> forming batch

    def submit(self, lowering, schema, time_bounds, ctx, adm, bc):
        m = _Member(lowering, schema, time_bounds, ctx)
        key = ctx.table_key
        cap = max(int(getattr(bc, "max_members", 16)), 2)
        with self._lock:
            batch = self._open.get(key)
            if batch is not None and not batch.closed and len(batch.members) < cap:
                batch.members.append(m)
                leader = False
            else:
                batch = _Batch()
                batch.members.append(m)
                self._open[key] = batch
                leader = True
        if leader:
            return self._lead(batch, m, key, adm, bc)
        # joiner: wait for the leader under this query's own deadline
        deadline = current_deadline()
        while not m.event.is_set():
            timeout = None if deadline is None else deadline - time.monotonic()
            if timeout is not None and timeout <= 0:
                check_deadline()
            m.event.wait(timeout if timeout is None else max(timeout, 0.001))
        if m.served:
            m.lowering.post_done = m.post_done
            tracing.add_event("dispatch.batched", table=key)
            flight_recorder.emit_adopted(flight_recorder.DispatchRecord(
                ts_ms=int(time.time() * 1000), table=key,
                trace_id=tracing.current_trace_id() or "",
                plan_fp=self._ex._recorder_fp(m.lowering, m.ctx),
                strategy="batched", flags=("batched",),
            ))
            return m.result
        # degrade: solo dispatch under this thread's own budget
        return self._ex._overload_safe_execute(
            m.lowering, m.schema, m.time_bounds, m.ctx, adm
        )

    def _lead(self, batch, m, key, adm, bc):
        # wait out the window for peers (bounded by the leader's own
        # remaining deadline), close the batch, run it, wake everyone.
        # The ENTIRE body sits under one try/finally: a leader dying in
        # the window sleep or the lock-close step (deadline alarm, async
        # interrupt, wedge-abandon raise) before the old finally was
        # entered used to strand every already-enqueued joiner on an
        # event nobody would ever set — they'd hang until their own
        # deadline instead of soloing immediately.  The finally both
        # closes the batch (so no NEW joiner can board a dead batch) and
        # wakes every peer with the solo-rerun verdict (served=False).
        try:
            window_s = min(float(bc.window_ms) / 1000.0, self._WINDOW_CAP_S)
            deadline = current_deadline()
            if deadline is not None:
                window_s = max(min(window_s, deadline - time.monotonic()), 0.0)
            if window_s > 0:
                time.sleep(window_s)
            with self._lock:
                batch.closed = True
                if self._open.get(key) is batch:
                    del self._open[key]
            try:
                self._run(batch, adm)
            except BaseException:  # noqa: BLE001 — every member degrades solo
                pass
        finally:
            with self._lock:
                batch.closed = True
                if self._open.get(key) is batch:
                    del self._open[key]
            for peer in batch.members:
                if peer is not m:
                    peer.event.set()
        if m.served:
            m.lowering.post_done = m.post_done
            return m.result
        return self._ex._overload_safe_execute(
            m.lowering, m.schema, m.time_bounds, m.ctx, adm
        )

    def _run(self, batch, adm):
        ex = self._ex
        # dedupe bit-identical (plan, snapshot) members: dupes adopt the
        # primary's result, exactly like admission coalescing would
        primaries: list[_Member] = []
        adopt: list[tuple[_Member, _Member]] = []
        by_key: dict = {}
        for m in batch.members:
            fk = ex._family_key(m.lowering, m.ctx)
            if fk is not None and fk in by_key:
                adopt.append((m, by_key[fk]))
                continue
            if fk is not None:
                by_key[fk] = m
            primaries.append(m)
        if len(primaries) == 1:
            # one unique plan: a plain solo dispatch (today's path, no
            # deferred fetch) — dupes below adopt it coalescing-style
            self._run_solo_into(primaries[0], adm)
        else:
            self._run_packed(primaries, adm)
        for dupe, prim in adopt:
            if prim.served:
                dupe.result = prim.result
                dupe.post_done = prim.post_done
                dupe.served = True
            else:
                dupe.solo = True

    def _run_solo_into(self, m: _Member, adm):
        try:
            m.result = self._ex._overload_safe_execute(
                m.lowering, m.schema, m.time_bounds, m.ctx, adm
            )
            m.post_done = m.lowering.post_done
            m.served = True
        except BaseException:  # noqa: BLE001 — owner thread owns the error
            m.solo = True

    def _fusion_enabled(self, bc) -> bool:
        if bc is None or not bool(getattr(bc, "fuse_programs", True)):
            return False
        # the fused trace replays the single-chip fold inline; the mesh
        # path shards planes across datanode devices with host-side
        # device_put hops that cannot ride one trace — it keeps
        # per-member dispatch.  Non-mesh multi-device hosts fuse: the
        # dispatcher colocates the member planes onto one chip first.
        try:
            return self._ex.cache.mesh_devices() == 0
        except Exception:  # noqa: BLE001 — unknowable topology: don't fuse
            return False

    def _run_fused(self, primaries: list[_Member], adm) -> list[_Member]:
        """Capture every member's dispatch, fuse the captured set into
        ONE XLA invocation, decode each member from the fused leaves.
        Returns the members the per-member packed path still owns:
        capture-ineligible members (their capture ran to a final answer
        or an injected `batch.fuse` capture fault marked them unfusable),
        plus EVERY captured member when the fused dispatch itself fails —
        degrade, never wrong."""
        ex = self._ex
        captured: list[tuple[_Member, CapturedDispatch]] = []
        leftover: list[_Member] = []
        for m in primaries:
            try:
                _fault_fire("batch.fuse", op="capture", table=m.ctx.table_key)
            except BaseException:  # noqa: BLE001 — member unfusable this tick
                leftover.append(m)
                continue
            try:
                with capture_dispatch():
                    out = ex._overload_safe_execute(
                        m.lowering, m.schema, m.time_bounds, m.ctx, adm
                    )
            except BaseException:  # noqa: BLE001 — degrade, never propagate
                m.solo = True
                continue
            if isinstance(out, CapturedDispatch):
                captured.append((m, out))
            else:
                # host fast path / cold serve / streamed / inapplicable:
                # the capture ran through to a final answer — the member
                # is already served, nothing to fuse for it
                m.result = out
                m.post_done = m.lowering.post_done
                m.served = True
        if len(captured) < 2:
            # nothing worth fusing: hand the captures back to the
            # per-member path (planes stay warm; relowering is cheap)
            leftover.extend(m for m, _ in captured)
            return leftover
        try:
            _fault_fire("batch.fuse", op="fuse", members=len(captured))
            tables, info = ex._fused_dispatch([cd for _, cd in captured])
        except BaseException:  # noqa: BLE001 — whole-tick degrade
            metrics.QUERY_BATCH_FUSE_DEGRADED_TOTAL.inc()
            leftover.extend(m for m, _ in captured)
            return leftover
        served = 0
        for (m, _cd), table in zip(captured, tables):
            if table is None:
                # rerun verdict (hash overflow / limb bound) or decode
                # failure: the solo rerun walks the full attempts ladder
                m.solo = True
                continue
            m.result = table
            m.post_done = m.lowering.post_done
            m.served = True
            served += 1
        metrics.QUERY_BATCH_DISPATCHES_TOTAL.inc()
        metrics.QUERY_BATCH_MEMBERS_TOTAL.inc(served)
        metrics.QUERY_BATCH_FUSED_DISPATCHES_TOTAL.inc()
        metrics.QUERY_BATCH_FUSE_MEMBERS.observe(float(len(captured)))
        flight_recorder.emit_fused_batch(
            table=captured[0][0].ctx.table_key,
            plan_fps=[
                ex._recorder_fp(m.lowering, m.ctx) for m, _ in captured
            ],
            members=len(captured),
            warmup=bool(info.get("traced")),
            stages_ms=info.get("stages_ms") or {},
            bytes_down=int(info.get("bytes_down") or 0),
        )
        return leftover

    def _run_packed(self, primaries: list[_Member], adm):
        ex = self._ex
        bc = getattr(ex.cache, "batch_config", None)
        if len(primaries) >= 2 and self._fusion_enabled(bc):
            primaries = self._run_fused(primaries, adm)
            if not primaries:
                return
        pendings: list[tuple[_Member, PendingFetch]] = []
        for m in primaries:
            # the member's own dispatch record (opened inside
            # _try_execute on THIS thread) carries the batched flag
            flight_recorder.flag_next("batched")
            try:
                with defer_fetch():
                    out = ex._overload_safe_execute(
                        m.lowering, m.schema, m.time_bounds, m.ctx, adm
                    )
            except BaseException:  # noqa: BLE001 — degrade, never propagate
                m.solo = True
                continue
            if isinstance(out, PendingFetch):
                pendings.append((m, out))
            else:
                # host fast path / inapplicable (None): already final
                m.result = out
                m.post_done = m.lowering.post_done
                m.served = True
        if not pendings:
            return
        try:
            _fault_fire(
                "batch.pack",
                members=len(pendings),
                leaves=sum(len(p.leaves) for _, p in pendings),
            )
            leaves = []
            for _, p in pendings:
                leaves.extend(p.leaves)
            t0 = time.perf_counter()
            with tracing.span("tile.batch_readback", members=len(pendings)):
                with rtt_sim.round_trip():
                    fetched = device_health.supervised_call(
                        "readback", lambda: jax.device_get(leaves)
                    )
            transfer_ms = (time.perf_counter() - t0) * 1000.0
        except BaseException:  # noqa: BLE001 — pack failure solos everyone
            for m, _ in pendings:
                m.solo = True
            return
        off = 0
        served = 0
        for m, p in pendings:
            part = fetched[off : off + len(p.leaves)]
            off += len(p.leaves)
            try:
                table = p.finish(part)
            except BaseException:  # noqa: BLE001 — degrade, never propagate
                m.solo = True
                continue
            if table is None:
                # rerun verdict (hash overflow / limb bound): the solo
                # rerun walks the full attempts ladder, exactly as today
                m.solo = True
                continue
            m.result = table
            m.post_done = m.lowering.post_done
            m.served = True
            served += 1
        if len(pendings) >= 2:
            metrics.QUERY_BATCH_DISPATCHES_TOTAL.inc()
            metrics.QUERY_BATCH_MEMBERS_TOTAL.inc(served)
            if flight_recorder.RECORDER.enabled:
                flight_recorder.RECORDER.emit(flight_recorder.DispatchRecord(
                    ts_ms=int(time.time() * 1000),
                    table=pendings[0][0].ctx.table_key,
                    trace_id=tracing.current_trace_id() or "",
                    plan_fp=",".join(
                        ex._recorder_fp(m.lowering, m.ctx) for m, _ in pendings
                    ),
                    strategy="batched", flags=("batched",),
                    stages_ms={"readback_transfer": round(transfer_ms, 3)},
                    bytes_down=int(
                        sum(getattr(a, "nbytes", 0) for a in fetched)
                    ),
                ))
