"""Cross-query device batching + windowed result cache.

The per-dispatch device->host round-trip (~100 ms on a remote-device
tunnel) dwarfs the warm compute (1-4 ms), so at dashboard-fleet QPS the
LINK, not the chip, is the bottleneck.  Admission coalescing (`
admission.coalesce`) already merges bit-identical concurrent plans onto
one dispatch; this module extends the same contract to DISTINCT plans:

  * `QueryBatcher` — warm queries against the same table that arrive
    within `batch.window_ms` of each other form a batch.  The first
    arrival is the LEADER: it waits out the window, then executes every
    member's dispatch back-to-back on the device stream in *deferred-
    fetch* mode (the executor returns a `PendingFetch` instead of
    fetching), flattens every member's packed output leaves and brings
    them home in ONE `jax.device_get` — one tunnel round-trip amortized
    across the whole batch — then runs each member's decode
    continuation host-side.  Members share the READBACK, never each
    other's math: each ran its own compiled program over its own plan,
    so results are bit-identical to solo runs by construction.  Any
    member that cannot be packed (dispatch error, decode verdict such
    as a hash-slot overflow, an injected `batch.pack` fault) degrades
    to its own solo dispatch on its own thread — batching can delay a
    query, never wrong it.  `batch.window_ms = 0` (the default)
    disables the layer entirely: today's path bit-for-bit.

  * `WindowedResultCache` — finished executor results keyed on
    (literal-insensitive plan fingerprint, filter-literal digest,
    bucket-aligned time window, per-region manifest version + WAL tail
    id).  A sliding dashboard that re-asks for the same aligned window
    re-serves with ZERO dispatch; any write moves the WAL tail and any
    flush/compaction bumps the manifest version, so stale entries are
    simply never reachable — the key IS the invalidation rule.  The
    snapshot versions are read BEFORE the query executes, so a write
    landing mid-query can only strand an unreachable old-versions
    entry, never publish a newer result under an older snapshot key.
    LRU-bounded by `batch.result_cache_mb` (0 = off).

Fault points: `batch.pack` fires immediately before the mega-readback;
`batch.result_cache` fires on every cache get/put.  Both degrade, never
corrupt: a pack failure solos every member, a cache failure is a miss.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict

import jax

from ..utils import flight_recorder, metrics, tracing
from ..utils.deadline import check_deadline, current_deadline
from ..utils.fault_injection import fire as _fault_fire

# ---- deferred device->host fetches -----------------------------------------
# Thread-local flag the batch leader raises around each member's dispatch:
# the executor's _finalize sees it and returns a PendingFetch (dispatched,
# unfetched) instead of paying a per-member device_get.

_DEFER = threading.local()


def defer_active() -> bool:
    return getattr(_DEFER, "active", False)


@contextlib.contextmanager
def defer_fetch():
    prev = getattr(_DEFER, "active", False)
    _DEFER.active = True
    try:
        yield
    finally:
        _DEFER.active = prev


@contextlib.contextmanager
def defer_suppressed():
    """Force eager fetches inside a deferred scope.  The region-streamed
    path releases each region's planes right after folding its partials,
    so its intermediate fetches must complete while the planes are
    guaranteed alive — it never defers."""
    prev = getattr(_DEFER, "active", False)
    _DEFER.active = False
    try:
        yield
    finally:
        _DEFER.active = prev


class PendingFetch:
    """One query's dispatched-but-unfetched packed device result: the
    output leaves still on device plus the decode continuation.  `finish`
    takes the host-fetched leaves (same order as `leaves`) and returns
    the decoded pa.Table — or None for a rerun verdict (hash-slot
    overflow / limb quantization bound), which the batcher turns into a
    solo degrade."""

    __slots__ = ("leaves", "finish")

    def __init__(self, leaves, finish):
        self.leaves = list(leaves)
        self.finish = finish


# ---- windowed result cache --------------------------------------------------


class WindowedResultCache:
    """LRU byte-bounded memo of finished executor results.

    Values are (pa.Table, post_done) — both immutable, so a hit hands
    back the stored objects directly.  `post_done` rides along because a
    device-finalized result already consumed some post-ops; the host
    replay must skip exactly those on a hit too, or the hit would
    double-apply LIMIT/HAVING."""

    # per-entry bookkeeping floor: a tiny table still costs key storage
    _ENTRY_OVERHEAD = 1 << 10

    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # key -> (table, post_done, nbytes)
        self._used = 0

    @staticmethod
    def key_for(executor, lowering, schema, ctx):
        """Cache key for one query, or None when not fingerprintable.

        (plan_fp, literals, window, versions): `plan_fp` is the literal-
        insensitive family fingerprint (filter STRUCTURE, bucket
        geometry); `literals` digests the filter values it elides;
        `window` is the effective scan time range, expressed in bucket
        units when both bounds sit exactly on the query's bucket grid
        (the canonical form a refreshing dashboard re-hits) and verbatim
        otherwise — both forms are exact, never merging windows that
        could select different rows; `versions` pins the data snapshot
        exactly like coalescing's `_family_key` does."""
        plan_fp = executor._plan_fp(lowering, ctx)
        if plan_fp is None:
            return None
        try:
            versions = tuple(
                (
                    r.region_id,
                    r.manifest_mgr.manifest.manifest_version,
                    r.wal.last_entry_id,
                )
                for r in ctx.regions
            )
            literals = repr(tuple(lowering.scan.filters))
            window = WindowedResultCache._window_key(lowering, schema)
        except Exception:  # noqa: BLE001 — fingerprinting is best-effort
            return None
        return (plan_fp, literals, window, versions)

    @staticmethod
    def _window_key(lowering, schema):
        tr = getattr(lowering.scan, "time_range", None)
        if tr is None:
            return ("full",)
        lo, hi = int(tr[0]), int(tr[1])
        bucket = getattr(lowering, "bucket", None)
        if bucket is not None and lo > -(1 << 61) and hi < (1 << 61):
            try:
                _ts, interval_ms, origin = bucket
                # same ms->native conversion as the plan's bucket geometry
                unit_ns = schema.time_index.data_type.timestamp_unit_ns()
                step = max(int(interval_ms * 1_000_000) // max(unit_ns, 1), 1)
                if (lo - origin) % step == 0 and (hi - origin) % step == 0:
                    # bijective given the plan: interval + origin are
                    # structural and already inside plan_fp
                    return ("aligned", (lo - origin) // step, (hi - origin) // step)
            except Exception:  # noqa: BLE001 — fall back to the verbatim form
                pass
        return ("raw", lo, hi)

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            return entry[0], entry[1]

    def put(self, key, table, post_done):
        try:
            nbytes = int(table.nbytes) + self._ENTRY_OVERHEAD
        except Exception:  # noqa: BLE001 — unsized results are uncacheable
            return
        if nbytes > self.budget:
            return
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._used -= old[2]
            self._entries[key] = (table, frozenset(post_done or ()), nbytes)
            self._used += nbytes
            while self._used > self.budget and self._entries:
                _, dropped = self._entries.popitem(last=False)
                self._used -= dropped[2]
                evicted += 1
        if evicted:
            metrics.QUERY_BATCH_RESULT_CACHE_EVICTIONS_TOTAL.inc(evicted)

    def purge_region(self, region_id: int):
        """Proactive drop of every entry touching the region.  The
        version-carrying key already makes stale entries unreachable;
        purging just returns their bytes to the budget immediately."""
        evicted = 0
        with self._lock:
            for key in list(self._entries):
                versions = key[3]
                if any(v[0] == region_id for v in versions):
                    self._used -= self._entries.pop(key)[2]
                    evicted += 1
        if evicted:
            metrics.QUERY_BATCH_RESULT_CACHE_EVICTIONS_TOTAL.inc(evicted)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._used}


# ---- the query batcher ------------------------------------------------------


class _Member:
    __slots__ = (
        "lowering", "schema", "time_bounds", "ctx",
        "event", "result", "post_done", "solo", "served",
    )

    def __init__(self, lowering, schema, time_bounds, ctx):
        self.lowering = lowering
        self.schema = schema
        self.time_bounds = time_bounds
        self.ctx = ctx
        self.event = threading.Event()
        self.result = None
        self.post_done = frozenset()
        self.solo = False  # degrade: owner thread runs its own solo dispatch
        self.served = False  # result/post_done came from the batch


class _Batch:
    __slots__ = ("members", "closed")

    def __init__(self):
        self.members: list[_Member] = []
        self.closed = False


class QueryBatcher:
    """Forms per-table batches of warm queries and runs each batch as
    back-to-back async dispatches sharing ONE packed readback.  The
    executor calls `submit` only for warm, fingerprintable families with
    `batch.window_ms > 0`; everything else takes the existing path."""

    # sanity ceiling on the leader's window sleep, whatever the knob says
    _WINDOW_CAP_S = 0.25

    def __init__(self, executor):
        self._ex = executor
        self._lock = threading.Lock()
        self._open: dict[str, _Batch] = {}  # table_key -> forming batch

    def submit(self, lowering, schema, time_bounds, ctx, adm, bc):
        m = _Member(lowering, schema, time_bounds, ctx)
        key = ctx.table_key
        cap = max(int(getattr(bc, "max_members", 16)), 2)
        with self._lock:
            batch = self._open.get(key)
            if batch is not None and not batch.closed and len(batch.members) < cap:
                batch.members.append(m)
                leader = False
            else:
                batch = _Batch()
                batch.members.append(m)
                self._open[key] = batch
                leader = True
        if leader:
            return self._lead(batch, m, key, adm, bc)
        # joiner: wait for the leader under this query's own deadline
        deadline = current_deadline()
        while not m.event.is_set():
            timeout = None if deadline is None else deadline - time.monotonic()
            if timeout is not None and timeout <= 0:
                check_deadline()
            m.event.wait(timeout if timeout is None else max(timeout, 0.001))
        if m.served:
            m.lowering.post_done = m.post_done
            tracing.add_event("dispatch.batched", table=key)
            flight_recorder.emit_adopted(flight_recorder.DispatchRecord(
                ts_ms=int(time.time() * 1000), table=key,
                trace_id=tracing.current_trace_id() or "",
                plan_fp=self._ex._recorder_fp(m.lowering, m.ctx),
                strategy="batched", flags=("batched",),
            ))
            return m.result
        # degrade: solo dispatch under this thread's own budget
        return self._ex._overload_safe_execute(
            m.lowering, m.schema, m.time_bounds, m.ctx, adm
        )

    def _lead(self, batch, m, key, adm, bc):
        # wait out the window for peers (bounded by the leader's own
        # remaining deadline), close the batch, run it, wake everyone
        window_s = min(float(bc.window_ms) / 1000.0, self._WINDOW_CAP_S)
        deadline = current_deadline()
        if deadline is not None:
            window_s = max(min(window_s, deadline - time.monotonic()), 0.0)
        if window_s > 0:
            time.sleep(window_s)
        with self._lock:
            batch.closed = True
            if self._open.get(key) is batch:
                del self._open[key]
        try:
            self._run(batch, adm)
        except BaseException:  # noqa: BLE001 — every member degrades solo
            pass
        finally:
            for peer in batch.members:
                if peer is not m:
                    peer.event.set()
        if m.served:
            m.lowering.post_done = m.post_done
            return m.result
        return self._ex._overload_safe_execute(
            m.lowering, m.schema, m.time_bounds, m.ctx, adm
        )

    def _run(self, batch, adm):
        ex = self._ex
        # dedupe bit-identical (plan, snapshot) members: dupes adopt the
        # primary's result, exactly like admission coalescing would
        primaries: list[_Member] = []
        adopt: list[tuple[_Member, _Member]] = []
        by_key: dict = {}
        for m in batch.members:
            fk = ex._family_key(m.lowering, m.ctx)
            if fk is not None and fk in by_key:
                adopt.append((m, by_key[fk]))
                continue
            if fk is not None:
                by_key[fk] = m
            primaries.append(m)
        if len(primaries) == 1:
            # one unique plan: a plain solo dispatch (today's path, no
            # deferred fetch) — dupes below adopt it coalescing-style
            self._run_solo_into(primaries[0], adm)
        else:
            self._run_packed(primaries, adm)
        for dupe, prim in adopt:
            if prim.served:
                dupe.result = prim.result
                dupe.post_done = prim.post_done
                dupe.served = True
            else:
                dupe.solo = True

    def _run_solo_into(self, m: _Member, adm):
        try:
            m.result = self._ex._overload_safe_execute(
                m.lowering, m.schema, m.time_bounds, m.ctx, adm
            )
            m.post_done = m.lowering.post_done
            m.served = True
        except BaseException:  # noqa: BLE001 — owner thread owns the error
            m.solo = True

    def _run_packed(self, primaries: list[_Member], adm):
        ex = self._ex
        pendings: list[tuple[_Member, PendingFetch]] = []
        for m in primaries:
            # the member's own dispatch record (opened inside
            # _try_execute on THIS thread) carries the batched flag
            flight_recorder.flag_next("batched")
            try:
                with defer_fetch():
                    out = ex._overload_safe_execute(
                        m.lowering, m.schema, m.time_bounds, m.ctx, adm
                    )
            except BaseException:  # noqa: BLE001 — degrade, never propagate
                m.solo = True
                continue
            if isinstance(out, PendingFetch):
                pendings.append((m, out))
            else:
                # host fast path / inapplicable (None): already final
                m.result = out
                m.post_done = m.lowering.post_done
                m.served = True
        if not pendings:
            return
        try:
            _fault_fire(
                "batch.pack",
                members=len(pendings),
                leaves=sum(len(p.leaves) for _, p in pendings),
            )
            leaves = []
            for _, p in pendings:
                leaves.extend(p.leaves)
            t0 = time.perf_counter()
            with tracing.span("tile.batch_readback", members=len(pendings)):
                fetched = jax.device_get(leaves)
            transfer_ms = (time.perf_counter() - t0) * 1000.0
        except BaseException:  # noqa: BLE001 — pack failure solos everyone
            for m, _ in pendings:
                m.solo = True
            return
        off = 0
        served = 0
        for m, p in pendings:
            part = fetched[off : off + len(p.leaves)]
            off += len(p.leaves)
            try:
                table = p.finish(part)
            except BaseException:  # noqa: BLE001 — degrade, never propagate
                m.solo = True
                continue
            if table is None:
                # rerun verdict (hash overflow / limb bound): the solo
                # rerun walks the full attempts ladder, exactly as today
                m.solo = True
                continue
            m.result = table
            m.post_done = m.lowering.post_done
            m.served = True
            served += 1
        if len(pendings) >= 2:
            metrics.QUERY_BATCH_DISPATCHES_TOTAL.inc()
            metrics.QUERY_BATCH_MEMBERS_TOTAL.inc(served)
            if flight_recorder.RECORDER.enabled:
                flight_recorder.RECORDER.emit(flight_recorder.DispatchRecord(
                    ts_ms=int(time.time() * 1000),
                    table=pendings[0][0].ctx.table_key,
                    trace_id=tracing.current_trace_id() or "",
                    plan_fp=",".join(
                        ex._recorder_fp(m.lowering, m.ctx) for m, _ in pendings
                    ),
                    strategy="batched", flags=("batched",),
                    stages_ms={"readback_transfer": round(transfer_ms, 3)},
                    bytes_down=int(
                        sum(getattr(a, "nbytes", 0) for a in fetched)
                    ),
                ))
