from ..utils.jax_env import ensure_x64

ensure_x64()

from .mesh import make_mesh, local_device_count
from .executor import DistGroupByPlan, distributed_groupby

__all__ = ["make_mesh", "local_device_count", "DistGroupByPlan", "distributed_groupby"]
