"""Distributed group-by execution over a device mesh.

TPU-native equivalent of the reference's distributed planner + MergeScan
(reference query/src/dist_plan/merge_scan.rs, commutativity.rs): each device
owns one region shard of the scan, computes the lower/state aggregate with
segment reductions, and the upper/merge aggregate rides an all-reduce
(psum/pmin/pmax) over the `regions` mesh axis — replacing the reference's
N:1 Flight stream merge at the frontend.

`compute_partial_states` below is the shared lower stage for BOTH this
table-fed mesh path and the HBM super-tile executor — including its
promoted multi-chip form (parallel/tile_cache.py `_mesh_merge_program`,
`tile.mesh_devices`), which runs the same per-source math under shard_map
and merges with the same psum/pmin/pmax collectives plus an
order-preserving fold for float sums.

Host-side responsibilities (the "frontend" role):
  - union tag dictionaries across region tables so codes agree globally
    (the reference ships dictionary mappings inside Flight IPC frames,
    common/grpc/src/flight.rs:48-63 — here codes must agree BEFORE upload);
  - pad every shard to one static shape and stack to [D, N];
  - decode finalized group ids back to (tags..., bucket timestamp) rows.

Cardinalities are quantized to powers of two so per-query recompiles are
bounded; out-of-range rows fall into the masked overflow slot.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import shard_map as _shard_map

from ..ops.aggregate import (
    BLOCK_ROWS,
    _FAST_MIN_ROWS,
    AggState,
    finalize,
    hash_group_slots,
    limb_segment_sums,
    psum_states,
    quantize_limbs,
    raw_group_ids,
    segment_aggregate,
    time_bucket,
)
from ..ops.tiles import TileBatch, padded_size, tiles_from_table
from .mesh import REGION_AXIS

COUNT_STAR = "__count_star"  # pseudo-column for count(*)

# SQL agg func -> kernel agg name
_FUNC_TO_KERNEL = {
    "sum": "sum",
    "count": "count",
    "min": "min",
    "max": "max",
    "avg": "avg",
    "last_value": "last",
}


@dataclass(frozen=True)
class DistGroupByPlan:
    """Static (hashable) description of a scan->filter->groupby aggregate.

    The jit cache key: two queries with the same plan structure share one
    compiled executable.  agg_specs is ((func, value_col), ...).
    """

    group_tags: tuple[str, ...]
    tag_cards: tuple[int, ...]
    bucket_col: str | None
    bucket_origin: int
    bucket_interval: int
    n_buckets: int
    agg_specs: tuple[tuple[str, str], ...]
    filters: tuple[tuple[str, str, object], ...] = ()
    acc_dtype: str = "float64"
    ts_col: str | None = None  # needed for last_value ordering
    # nullable filter columns whose present-mask must gate the row mask
    # (SQL: NULL never satisfies a predicate); the table-based path
    # pre-filters on the host so this only matters for the tile path
    filter_null_cols: tuple[str, ...] = ()
    # Hierarchical grouping (ops/aggregate.py reduce_state_axes): when the
    # requested group keys are not a primary-key prefix in pk order, the
    # group id is composed over this pk prefix instead (+ bucket last), the
    # blocked kernel aggregates at that finer layout-clustered granularity,
    # and the state is folded down to `group_tags` on device.
    layout_tags: tuple[str, ...] | None = None
    layout_cards: tuple[int, ...] = ()
    # Time-major execution: sources are gathered through a ts-ascending
    # permutation before aggregation, making `gid = bucket` globally
    # non-decreasing for ANY bucket interval (bucket-only group-bys like
    # TSBS single-groupby / groupby-orderby-limit).
    time_major: bool = False
    # Blocked-kernel span (ops/aggregate.py): sized by the planner from
    # expected groups-per-block so layouts with more than 16 consecutive
    # groups per 4096-row block (e.g. hour buckets over long windows)
    # still take the scatter-free kernel.
    block_span: int = 16
    # Device group-by strategy (the `agg_strategy` planner pass):
    # "sort" = the dense mixed-radix path above (states are [G], the
    # (pk, ts) sort makes the blocked kernel engage);
    # "hash" = group ids hash into a `hash_slots`-sized device table
    # (ops/aggregate.hash_group_slots) threaded through every source of
    # the query, states are [hash_slots + 1] and the host decodes slot ->
    # group key from the table — the dense [G] space never materializes,
    # so group spaces far past max_groups stay executable.
    agg_strategy: str = "sort"
    hash_slots: int = 0

    @property
    def num_groups(self) -> int:
        """Output group-space size (the [G] the caller sees)."""
        g = 1
        for c in self.tag_cards:
            g *= c
        if self.bucket_col is not None:
            g *= self.n_buckets
        return g

    @property
    def internal_groups(self) -> int:
        """Stage-1 group-space size (= num_groups unless hierarchical)."""
        if self.layout_tags is None:
            return self.num_groups
        g = 1
        for c in self.layout_cards:
            g *= c
        if self.bucket_col is not None:
            g *= self.n_buckets
        return g

    def value_cols(self) -> list[str]:
        out = []
        for _f, c in self.agg_specs:
            if c != COUNT_STAR and c not in out:
                out.append(c)
        return out


def streamed_device_get(parts: list, chunk_bytes: int = 1 << 20) -> list:
    """Chunked device->host fetch with transfer/host-copy overlap: each
    part is sliced (flat) into ~chunk_bytes device_gets, and slice i+1's
    transfer is in flight on a helper thread while slice i copies into
    its preallocated host destination — the host-side "decode" work rides
    under the wire time instead of serializing after it.  The caller's
    one-logical-fetch contract holds: this IS the query's single result
    readback, just pipelined.

    Returns numpy arrays matching `parts`' shapes/dtypes, bit-identical
    to a plain jax.device_get (tests assert it)."""
    outs: list[np.ndarray] = []
    flats: list = []
    jobs: list[tuple[int, int, int]] = []
    for pi, p in enumerate(parts):
        out = np.empty(p.shape, np.dtype(p.dtype))
        outs.append(out)
        flats.append(p.reshape(-1))
        n = int(out.size)
        if n == 0:
            continue
        per = max(chunk_bytes // max(out.itemsize, 1), 1)
        for a in range(0, n, per):
            jobs.append((pi, a, min(a + per, n)))
    if not jobs:
        return outs

    def fetch(job):
        # the device slice materializes HERE, just before its fetch, so
        # at most two slices are alive at once — building every slice up
        # front would dispatch all of them and double the result's device
        # footprint on exactly the memory-pressured paths streaming is for
        pi, a, b = job
        return jax.device_get(flats[pi][a:b])

    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="readback"
    ) as pool:
        fut = pool.submit(fetch, jobs[0])
        for i, (pi, a, b) in enumerate(jobs):
            got = fut.result()
            if i + 1 < len(jobs):
                fut = pool.submit(fetch, jobs[i + 1])
            outs[pi].reshape(-1)[a:b] = got
    return outs


def _quantize_card(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p <<= 1
    return p


def _apply_filters(plan: DistGroupByPlan, columns, mask, values=None):
    """Evaluate pushed-down predicates.  `values` (optional) supplies the
    literals as RUNTIME arguments — the tile path passes them dynamically
    so changing a literal reuses the compiled program; the mesh path bakes
    them into the plan (position i of `values` pairs with filter i)."""
    for i, (name, op, static_v) in enumerate(plan.filters):
        value = static_v if values is None else values[i]
        col = columns[name]
        if op == "=":
            mask = mask & (col == value)
        elif op == "!=":
            mask = mask & (col != value)
        elif op == "<":
            mask = mask & (col < value)
        elif op == "<=":
            mask = mask & (col <= value)
        elif op == ">":
            mask = mask & (col > value)
        elif op == ">=":
            mask = mask & (col >= value)
        elif op == "in":
            m = jnp.zeros_like(mask)
            for v in value:
                m = m | (col == v)
            mask = mask & m
        elif op == "not in":
            for v in value:
                mask = mask & (col != v)
    return mask


def compute_partial_states(plan: DistGroupByPlan, columns, valid, nulls, dyn=None, perm=None, count_cols=None, limbs=None, hash_table=None):
    """Shared lower/state stage: mask -> group ids -> partial AggStates.
    No collectives — callers merge across devices (psum) or across tile
    sources (merge_states).  `dyn` optionally carries runtime-dynamic plan
    parameters: {'filter_values', 'bucket_origin', 'bucket_interval'} —
    only shapes (cards, n_buckets, filter structure) stay compile-static.
    `perm` (time-major plans) re-gathers every per-row array into
    ts-ascending order first, so bucket-composed gids are sorted.
    `count_cols` fixes WHICH columns carry their own null-gated count
    pass: multi-source callers (the tile program) must pass the union
    decision so every source produces structurally identical AggStates —
    deciding per-source from `col in nulls` made merge_states silently
    drop counts (or crash) when sources disagreed on a column's
    nullability.  None = decide from this source's nulls (single-source
    mesh path).

    `plan.acc_dtype == "limb"` routes sum/avg/count columns through the
    MXU limb kernel (ops/aggregate.py `limb_segment_sums`) — one batched
    matmul for ALL such columns instead of a per-column VPU pass; min/max/
    last keep the f64 blocked kernels.  `limbs` optionally supplies cached
    quantized planes per column (dict col -> (limbs, scale)); missing
    columns quantize in-program from their f64 plane.

    With `plan.agg_strategy == "hash"` the caller must pass `hash_table`
    (the [hash_slots] int64 key table threaded across this query's
    sources) and gets back `(states, hash_table')`: group ids are
    composed in int64 (the sparse space may exceed int32), hashed to
    compact slots, and every kernel aggregates into [hash_slots + 1]
    scatter-space — the dense [G] never exists on device.  States carry
    an extra `__hash_overflow` row counting rows the table could not
    place (sum-merged across sources) so the executor can fall back to
    the dense path instead of ever returning a wrong result."""
    acc = jnp.float32 if plan.acc_dtype == "float32" else jnp.float64
    if perm is not None:
        columns = {k: v[perm] for k, v in columns.items()}
        valid = valid[perm]
        nulls = {k: v[perm] for k, v in nulls.items()}
        # cached limb planes encode the UNpermuted block layout — they
        # cannot be row-gathered (block scales would be wrong); callers
        # with a perm must supply order-matched limbs (time-major planes)
        # or none at all
        limbs = None
    mask = _apply_filters(
        plan, columns, valid, None if dyn is None else dyn["filter_values"]
    )
    for c in plan.filter_null_cols:
        if c in nulls:
            mask = mask & nulls[c]

    components: list[tuple[jnp.ndarray, int]] = []
    if plan.layout_tags is not None:
        for tag, card in zip(plan.layout_tags, plan.layout_cards):
            components.append((columns[tag], card))
    else:
        for tag, card in zip(plan.group_tags, plan.tag_cards):
            components.append((columns[tag], card))
    if plan.bucket_col is not None:
        origin = plan.bucket_origin if dyn is None else dyn["bucket_origin"]
        interval = plan.bucket_interval if dyn is None else dyn["bucket_interval"]
        b = time_bucket(columns[plan.bucket_col], origin, interval)
        components.append((b, plan.n_buckets))
    is_hash = plan.agg_strategy == "hash"
    overflow = None
    if is_hash:
        if hash_table is None:
            raise ValueError("hash agg strategy requires the threaded hash_table")
        # int64 ids: the SPARSE space may exceed int32 — it never
        # materializes, only its occupied keys do (one per table slot)
        gid64, in_range = raw_group_ids(
            components, shape=valid.shape, dtype=jnp.int64
        )
        active = mask & in_range
        hash_table, gids, overflow = hash_group_slots(hash_table, gid64, active)
        mask = active
        n_internal = plan.hash_slots
    else:
        n_internal = plan.internal_groups
        # raw in-range ids + mask (NOT overflow-encoded): keeps scan-order
        # sortedness intact so segment_aggregate's block kernel can engage.
        # Tail padding rows (valid=False) get the max id so they don't break
        # the ascending-order guard; their mask keeps them out of every sum.
        gids, in_range = raw_group_ids(components, shape=valid.shape)
        mask = mask & in_range
        gids = jnp.where(valid, gids, n_internal - 1)

    ts = None
    if plan.ts_col is not None and plan.ts_col in columns:
        ts = columns[plan.ts_col]

    # Columns sharing an aggregate set are STACKED into one
    # segment_aggregate_multi call — one layout guard, one compiled branch
    # trio, vmapped over columns (compile and guard cost stop scaling with
    # column count).  "count" is always included: it doubles as the
    # per-column null mask for SQL NULL semantics (sum over an all-null
    # group is NULL, not 0).  last_value keeps the per-column path (needs
    # the ts-ordered two-pass kernel).
    from ..ops.aggregate import reduce_state_axes, segment_aggregate_multi

    if plan.layout_tags is not None:
        fold_cards = plan.layout_cards + (
            (plan.n_buckets,) if plan.bucket_col is not None else ()
        )
        keep_axes = tuple(plan.layout_tags.index(t) for t in plan.group_tags) + (
            (len(plan.layout_tags),) if plan.bucket_col is not None else ()
        )

        def fold(state: AggState) -> AggState:
            return reduce_state_axes(state, fold_cards, keep_axes)
    else:
        def fold(state: AggState) -> AggState:
            return state

    per_col_aggs: dict[str, set] = {}
    for func, col in plan.agg_specs:
        per_col_aggs.setdefault(col, set()).add(_FUNC_TO_KERNEL[func])
    states = {}
    groups: dict[tuple, list[str]] = {}
    last_presence: str | None = None
    n_rows = valid.shape[0]
    # Limb routing is decided from the PLAN alone (never per-source size):
    # every source of a multi-source program must emit structurally
    # identical AggStates or merge_states breaks — sources too small for
    # the limb geometry take segment_sums_scatter, which produces the
    # same trio exactly.
    limb_mode = plan.acc_dtype == "limb"
    limb_fits = n_rows >= _FAST_MIN_ROWS and n_rows % BLOCK_ROWS == 0
    limb_batch: list[tuple[str, bool]] = []  # (col, counted)
    for col, aggs in per_col_aggs.items():
        if "last" in aggs:
            # LAST has no reshape-reduce fold; the planner never builds a
            # hierarchical plan with last_value
            key = tuple(sorted(aggs | {"count"}))
            col_mask = mask & nulls[col] if col in nulls else mask
            if col not in nulls:
                last_presence = col  # its count IS the presence count
            states[col] = fold(segment_aggregate(
                columns[col], gids, n_internal, key,
                mask=col_mask, ts=ts, acc_dtype=acc, span=plan.block_span,
                force_scatter=is_hash,
            ))
            continue
        # Count-pass sharing: for a column with NO null mask, its count
        # equals the group presence count, so the per-column kernel skips
        # the count pass entirely — at TSBS scale (10 avg columns, no
        # nulls) this halves device work.  Null-bearing columns keep their
        # own count (SQL NULL-gating).  count(*) is presence by definition.
        if col == COUNT_STAR:
            continue  # presence covers it
        null_gated = (col in count_cols) if count_cols is not None else (col in nulls)
        kernel_aggs = set()
        if "sum" in aggs or "avg" in aggs:
            kernel_aggs.add("sum")
        if "min" in aggs:
            kernel_aggs.add("min")
        if "max" in aggs:
            kernel_aggs.add("max")
        if null_gated:
            kernel_aggs.add("count")
        elif not kernel_aggs:
            continue  # count(col) on a non-null column: presence covers it
        if limb_mode and "sum" in kernel_aggs:
            # sum + null-gated count ride the MXU batch; min/max (order
            # statistics have no matmul form) keep the blocked kernel,
            # and count-only columns stay on their near-free count pass
            limb_batch.append((col, null_gated))
            kernel_aggs -= {"sum", "count"}
        if kernel_aggs:
            groups.setdefault(tuple(sorted(kernel_aggs)), []).append(col)
    # Presence fusing: a NON-null-gated value column counts exactly the
    # base-mask rows, which IS the group presence — ride its kernel pass
    # (the count reduction fuses with the column's sum/min/max over the
    # same one-hot, nearly free) instead of spending a whole separate
    # pass on a pseudo-column.  Only when every column is null-gated (or
    # there are none) does presence pay its own pass.  The limb batch
    # carries presence for free (its ones column), so it wins outright.
    presence_from: str | None = None
    if not limb_batch:
        for key in list(groups):
            if "count" in key:
                continue
            cols = groups[key]
            rep = cols[0]
            if len(cols) == 1:
                del groups[key]
            else:
                groups[key] = cols[1:]
            groups.setdefault(tuple(sorted(set(key) | {"count"})), []).insert(0, rep)
            presence_from = rep
            break
        if presence_from is None and last_presence is not None:
            presence_from = last_presence
        if presence_from is None:
            # pseudo-column whose "values" are the mask itself
            groups.setdefault(("count",), []).append("__presence")
    for key, cols in groups.items():
        # per-column lists, never a stacked [C, n] (HBM: see
        # segment_aggregate_multi); count-only pseudo-columns reuse the
        # mask as a dummy values array — counts come from the mask alone
        vals = [
            mask if c in ("__presence", COUNT_STAR) else columns[c].astype(acc)
            for c in cols
        ]
        col_masks = [
            mask & nulls[c] if c in nulls else mask
            for c in cols
        ]
        multi = segment_aggregate_multi(
            vals, gids, n_internal, key, col_masks, mask, acc_dtype=acc,
            span=plan.block_span, force_scatter=is_hash,
        )
        for i, c in enumerate(cols):
            states[c] = fold(AggState(
                sums=None if multi.sums is None else multi.sums[i],
                counts=None if multi.counts is None else multi.counts[i],
                mins=None if multi.mins is None else multi.mins[i],
                maxs=None if multi.maxs is None else multi.maxs[i],
            ))
    if limb_batch:
        count01 = [
            nulls[c] if (counted and c in nulls) else None
            for c, counted in limb_batch
        ]
        any_counted = any(counted for _c, counted in limb_batch)
        c01 = count01 if any_counted else None
        if limb_fits:
            limb_inputs = []
            for c, _counted in limb_batch:
                if limbs is not None and c in limbs:
                    limb_inputs.append(limbs[c])
                else:
                    limb_inputs.append(quantize_limbs(columns[c]))
            lsums, lerrs, lcounts, lpresence = limb_segment_sums(
                limb_inputs, gids, mask, n_internal, plan.block_span,
                count01=c01,
            )
        else:
            from ..ops.aggregate import segment_sums_scatter

            lsums, lerrs, lcounts, lpresence = segment_sums_scatter(
                [columns[c] for c, _counted in limb_batch],
                gids, mask, n_internal, count01=c01,
            )
        for i, (c, counted) in enumerate(limb_batch):
            st = fold(AggState(
                sums=lsums[i],
                counts=lcounts[i] if counted else None,
            ))
            prev = states.get(c)
            if prev is not None:  # min/max part from the blocked kernel
                st = AggState(
                    sums=st.sums, counts=st.counts,
                    mins=prev.mins, maxs=prev.maxs,
                    last_ts=prev.last_ts, last_val=prev.last_val,
                )
            states[c] = st
            # worst-case quantization error bound per group: merges by
            # addition and folds like a sum — the tile program checks it
            # against |sum| and reruns in exact f64 when it's too loose
            states["__limb_err:" + c] = fold(AggState(sums=lerrs[i]))
        states["__presence"] = fold(AggState(counts=lpresence))
    elif presence_from is not None:
        states["__presence"] = AggState(counts=states[presence_from].counts)
    if is_hash:
        # sum-merges across sources like any count; > 0 after the final
        # merge means some row never found a slot -> dense-path rerun
        states["__hash_overflow"] = AggState(counts=overflow.reshape(1))
        return states, hash_table
    return states


def _device_step(plan: DistGroupByPlan, columns, valid, nulls):
    """Per-device: partial states then psum merge over the mesh axis.
    Runs under shard_map; `nulls` maps value col -> present-mask."""
    states = compute_partial_states(plan, columns, valid, nulls)
    return {k: psum_states(v, REGION_AXIS) for k, v in states.items()}


@functools.lru_cache(maxsize=64)
def _compiled_step(mesh: Mesh, plan: DistGroupByPlan):
    def per_device(cols, valid, nulls):
        cols = {k: v[0] for k, v in cols.items()}
        nulls = {k: v[0] for k, v in nulls.items()}
        return _device_step(plan, cols, valid[0], nulls)

    sharded = _shard_map(
        per_device,
        mesh=mesh,
        in_specs=P(REGION_AXIS, None),
        out_specs=P(),
    )
    return jax.jit(sharded)


def host_last_winners(g, t, v, lexsort_cap: int = 1 << 22):
    """Numpy twin of the device last_value kernel for ONE source range:
    one (gid, ts, value) winner per gid present in `g`, where the winner
    is the max-ts row and a ts tie resolves to the LAST row in scan order
    (the device `_segment_blocked_last` highest-row-index rule — layout is
    (pk, ts, write-order) sorted, so that is exactly last-write-wins).

    Rows already sorted (gid non-decreasing, ts non-decreasing within each
    gid run) take the O(n)-compare run-boundary path; unsorted tails
    lexsort, whose STABLE order preserves the same tie rule.  Returns
    None when the range is unsorted beyond `lexsort_cap` rows (callers
    fall back to the device path).  Cross-source merging is the caller's
    job: fold winners in source order with ties going to the later source
    (`merge_states`' newer_or_tie rule)."""
    if not len(g):
        return g[:0], t[:0], v[:0]
    runs_ok = bool(np.all(g[1:] >= g[:-1])) and bool(
        np.all((g[1:] != g[:-1]) | (t[1:] >= t[:-1]))
    )
    if not runs_ok:
        if len(g) > lexsort_cap:
            return None
        order = np.lexsort((t, g))
        g, t, v = g[order], t[order], v[order]
    ends = np.append(np.flatnonzero(g[1:] != g[:-1]), len(g) - 1)
    return g[ends], t[ends], v[ends]


@dataclass
class GroupByResult:
    """Finalized aggregates plus the host-side group key decode."""

    outputs: dict[str, np.ndarray]  # "func(col)" -> [G]
    non_empty: np.ndarray
    tag_values: dict[str, list]
    plan: DistGroupByPlan
    # actual bucket geometry when the plan carries dynamic placeholders
    bucket_origin: int | None = None
    bucket_interval: int | None = None

    def to_table(self) -> pa.Table:
        idx = np.nonzero(self.non_empty)[0]
        cols: dict[str, object] = {}
        dims: list[tuple[str, int]] = list(zip(self.plan.group_tags, self.plan.tag_cards))
        if self.plan.bucket_col is not None:
            dims.append(("__bucket", self.plan.n_buckets))
        decoded = {}
        div = 1
        for name, card in reversed(dims):
            decoded[name] = (idx // div) % card
            div *= card
        for tag in self.plan.group_tags:
            values = self.tag_values.get(tag, [])
            codes = decoded[tag]
            cols[tag] = [values[c] if c < len(values) else None for c in codes]
        if self.plan.bucket_col is not None:
            origin = (
                self.bucket_origin
                if self.bucket_origin is not None
                else self.plan.bucket_origin
            )
            interval = (
                self.bucket_interval
                if self.bucket_interval is not None
                else self.plan.bucket_interval
            )
            ts = origin + decoded["__bucket"].astype(np.int64) * interval
            cols[self.plan.bucket_col] = ts
        for name, arr in self.outputs.items():
            sel = np.asarray(arr)[idx]
            if np.issubdtype(sel.dtype, np.floating):
                cols[name] = pa.array(sel, mask=np.isnan(sel))  # NaN -> NULL
            else:
                cols[name] = pa.array(sel)
        return pa.table(cols)


def distributed_groupby(
    mesh: Mesh,
    region_tables: list[pa.Table],
    *,
    group_tags: list[str],
    bucket_col: str | None,
    bucket_origin: int,
    bucket_interval: int,
    n_buckets: int,
    agg_specs: list[tuple[str, str]] | None = None,
    # Backwards-compatible single-column form:
    value_col: str | None = None,
    aggs: tuple[str, ...] | None = None,
    filters: list[tuple[str, str, object]] | None = None,
    acc_dtype: str = "float64",
    tile_rows: int = 1 << 20,
    ts_col: str | None = None,
) -> GroupByResult:
    """Execute a scan->filter->time-bucketed-groupby over region tables."""
    n_dev = mesh.devices.size
    filters = filters or []
    if agg_specs is None:
        assert value_col is not None and aggs is not None
        agg_specs = [(("avg" if a == "avg" else a), value_col) for a in aggs]
    # Normalize func names (count(*) -> COUNT_STAR pseudo column).
    norm_specs: list[tuple[str, str]] = []
    for func, col in agg_specs:
        if func == "count" and col is None:
            col = COUNT_STAR
        norm_specs.append((func, col))

    # 1. Distribute tables over device slots (round-robin concat).
    slots: list[list[pa.Table]] = [[] for _ in range(n_dev)]
    for i, t in enumerate(region_tables):
        slots[i % n_dev].append(t)
    slot_tables = [
        pa.concat_tables(ts, promote_options="permissive") if ts else None for ts in slots
    ]
    if all(t is None for t in slot_tables):
        raise ValueError("no region tables to scan")

    # 2. Union tag dictionaries across shards so codes agree globally.
    value_cols = [c for _f, c in norm_specs if c != COUNT_STAR]
    needed_cols = set(group_tags) | set(value_cols) | {f[0] for f in filters}
    if bucket_col is not None:
        needed_cols.add(bucket_col)
    if ts_col is not None:
        needed_cols.add(ts_col)
    union_dicts: dict[str, dict] = {}
    for t in slot_tables:
        if t is None:
            continue
        for name in t.column_names:
            if name not in needed_cols:
                continue
            col = t[name]
            typ = col.type
            if pa.types.is_dictionary(typ):
                typ = typ.value_type
            if pa.types.is_string(typ) or pa.types.is_large_string(typ) or pa.types.is_binary(typ):
                mapping = union_dicts.setdefault(name, {})
                if col.type != typ:
                    col = col.cast(typ)
                for v in pc.unique(col).to_pylist():
                    if v not in mapping:
                        mapping[v] = len(mapping)

    # 3. Tile each shard to ONE padded size.
    max_rows = max((t.num_rows if t is not None else 0) for t in slot_tables)
    padded = padded_size(max_rows, tile_rows)
    empty_schema = next(t for t in slot_tables if t is not None).schema
    batches: list[TileBatch] = []
    for t in slot_tables:
        if t is None:
            t = empty_schema.empty_table()
        t = t.select([c for c in t.column_names if c in needed_cols])
        batches.append(tiles_from_table(t, tile_rows=padded, dicts=union_dicts))

    # 4. Stack shards to [D, N] host arrays.
    col_names = tuple(sorted(batches[0].columns))
    cols_stacked = {k: jnp.stack([b.columns[k] for b in batches]) for k in col_names}
    valid_stacked = jnp.stack([b.valid for b in batches])
    ones = jnp.ones(padded, dtype=bool)
    nulls_stacked = {
        c: jnp.stack([b.nulls.get(c, ones) for b in batches])
        for c in value_cols
        if any(c in b.nulls for b in batches)  # all-ones masks would defeat
        # count-pass sharing and ship [D, N] bools for nothing
    }

    # 5. Encode filter literals to codes; quantize cardinalities.
    enc_filters = []
    for name, op, value in filters:
        if name in union_dicts:
            if op in ("in", "not in"):
                value = tuple(union_dicts[name].get(v, -1) for v in value)
            else:
                value = union_dicts[name].get(value, -1)
        elif op in ("in", "not in"):
            value = tuple(value)
        enc_filters.append((name, op, value))
    tag_cards = tuple(_quantize_card(len(union_dicts.get(t, {}))) for t in group_tags)

    needs_ts = any(f == "last_value" for f, _c in norm_specs)
    plan = DistGroupByPlan(
        group_tags=tuple(group_tags),
        tag_cards=tag_cards,
        bucket_col=bucket_col,
        bucket_origin=bucket_origin,
        bucket_interval=bucket_interval,
        n_buckets=n_buckets,
        agg_specs=tuple(norm_specs),
        filters=tuple(enc_filters),
        acc_dtype=acc_dtype,
        ts_col=(ts_col or bucket_col) if needs_ts else None,
    )

    # 6. Compile + run + finalize.
    import time as _time

    from ..utils import flight_recorder

    t0 = _time.perf_counter()
    step = _compiled_step(mesh, plan)
    flight_recorder.stage_add(
        "compile", (_time.perf_counter() - t0) * 1000.0
    )
    from ..utils import device_health as _device_health

    mesh_slots = tuple(range(int(mesh.devices.size)))
    t0 = _time.perf_counter()
    states = _device_health.supervised_call(
        "dispatch",
        lambda: step(cols_stacked, valid_stacked, nulls_stacked),
        devices=mesh_slots,
    )
    flight_recorder.stage_add(
        "dispatch", (_time.perf_counter() - t0) * 1000.0
    )
    flight_recorder.note(
        strategy="mesh_table", mesh_devices=int(mesh.devices.size)
    )

    outputs: dict[str, np.ndarray] = {}
    per_col_aggs: dict[str, set] = {}
    for func, col in norm_specs:
        per_col_aggs.setdefault(col, set()).add(_FUNC_TO_KERNEL[func])
    presence = states["__presence"].counts
    finals = {
        col: finalize(states[col], tuple(sorted(aggs)), counts=presence)
        for col, aggs in per_col_aggs.items()
        if col in states
    }
    # ONE batched device->host fetch of every finalized row (the per-array
    # np.asarray conversions below each paid a link round-trip on the
    # remote harness), metered as transfer time so readback stays
    # attributable on the mesh path too
    from ..utils import metrics as _metrics

    t0 = _time.perf_counter()
    presence_np, finals = _device_health.supervised_call(
        "readback",
        lambda: jax.device_get((presence, finals)),
        devices=mesh_slots,
    )
    fetch_ms = (_time.perf_counter() - t0) * 1000.0
    _metrics.TPU_READBACK_TRANSFER_MS.observe(fetch_ms)
    flight_recorder.stage_add("readback_transfer", fetch_ms)
    flight_recorder.add_bytes(down=int(
        np.asarray(presence_np).nbytes
        + sum(
            np.asarray(a).nbytes
            for d in finals.values()
            for a in d.values()
        )
    ))
    presence_np = np.asarray(presence_np)
    non_empty = presence_np > 0
    for func, col in norm_specs:
        out = finals.get(col, {})
        kernel = _FUNC_TO_KERNEL[func]
        arr = out.get(kernel)
        if arr is None and kernel == "count":
            arr = presence_np  # count-pass sharing: presence IS the count
        arr = np.asarray(arr)
        col_count = np.asarray(out.get("count", presence_np))
        if col == COUNT_STAR:
            outputs["count(*)"] = arr.astype(np.int64)
        elif func == "count":
            outputs[f"count({col})"] = arr.astype(np.int64)
        else:
            # NULL semantics: no non-null values in the group -> NULL output.
            outputs[f"{func}({col})"] = np.where(col_count > 0, arr, np.nan)

    tag_values = {}
    for tag in group_tags:
        mapping = union_dicts.get(tag, {})
        values = [None] * len(mapping)
        for v, code in mapping.items():
            values[code] = v
        tag_values[tag] = values
    return GroupByResult(outputs=outputs, non_empty=non_empty, tag_values=tag_values, plan=plan)
