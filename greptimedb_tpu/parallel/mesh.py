"""Device mesh construction for distributed query execution.

The DB analogue of the reference's cluster topology: the mesh's `regions`
axis plays the role of datanodes (each device scans+partially aggregates its
region shard, reference merge_scan.rs fan-out), and the merge happens with
XLA collectives over ICI instead of N:1 Flight streams.  Multi-host pods
extend the same mesh over DCN — jax arranges the collectives; we only
annotate shardings (scaling-book recipe).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

REGION_AXIS = "regions"


def local_device_count() -> int:
    return jax.local_device_count()


def make_mesh(
    n_devices: int | None = None,
    axis: str = REGION_AXIS,
    devices: list | None = None,
) -> Mesh:
    """1-D mesh over (up to) n_devices local devices.

    A 1-D `regions` axis is the right shape for scan fan-out + all-reduce
    merge; model-parallel style 2-D meshes are unnecessary because the DB
    hot path has no weight matrices to shard.

    Pass an explicit `devices` list to build the mesh over a subset — the
    device-health supervisor shrinks the mesh to the surviving (healthy)
    device set this way after a quarantine.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def region_device_index(region_id: int, n_devices: int) -> int:
    """Stable region -> mesh-device slot (the co-location contract).

    One mapping shared by the tile cache's chunk placement and the
    frontend's fan-out ordering: a region's super-tile chunks live on
    (a run starting at) this device, and the frontend visits regions in
    device order, so the scan fan-out of a datanode's regions is
    device-local instead of scattering every region's first chunk onto
    device 0.  Mirrors the reference co-locating a region's MergeScan
    stream with its owning datanode."""
    if n_devices <= 0:
        return 0
    return int(region_id) % int(n_devices)
