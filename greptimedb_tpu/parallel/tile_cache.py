"""HBM-resident SST tile cache + single-dispatch aggregation executor.

This is the engine's answer to "the tiles are resident in HBM": instead of
re-reading Parquet, re-encoding tags and re-uploading columns on every query
(the round-1 hot path), each SST file's needed columns are encoded ONCE —
tag strings to stable per-table dictionary codes (storage/dictionary.py),
timestamps to int64, values to float — and kept on the device, keyed by
(region, file, column).  A query then:

  1. snapshots each region's (files, memtables) under the region lock,
  2. fetches/repairs cached file tiles (dictionary growth is repaired with
     one gather using the recorded code permutation — no Parquet re-read),
  3. encodes only the memtable tail (small, vectorized),
  4. runs ONE jit-compiled program that computes per-source partial
     AggStates with the shared kernels (ops/aggregate.py) and merges them —
     per-source processing preserves each file's (pk, ts) sort order so the
     sorted-block kernel engages per source,
  5. finalizes [G]-sized states on the host.

Role-equivalents in the reference: the write/page caches
(mito2/src/cache/write_cache.rs, cache.rs — "upload on flush, serve reads
from local media"; here the medium is HBM) and the pre-encoded primary keys
(mito-codec/src/row_converter/).

Correctness gate: the tile path aggregates raw file rows WITHOUT the
last-write-wins dedup pass a normal scan performs, so it only engages when
dedup is provably a no-op:
  * the table is append_mode (duplicates are semantically kept), or
  * every pair of sources (SST files + memtable) has disjoint inclusive
    time ranges — two versions of one row need equal timestamps;
and never when any source holds delete tombstones or a file predates
tombstone accounting (FileMeta.num_deletes < 0).  Anything else returns
None and the authoritative scan path runs.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..ops.aggregate import finalize, merge_states
from ..ops.tiles import padded_size
from ..storage.dictionary import TableDictionary
from ..storage.region import OP_COL, Region
from ..storage.sst import FileMeta, ScanPredicate
from ..utils import metrics
from .executor import (
    COUNT_STAR,
    DistGroupByPlan,
    GroupByResult,
    _FUNC_TO_KERNEL,
    _quantize_card,
    compute_partial_states,
)

TILE_QUANTUM = 1 << 14  # pad granularity for every source: bounds recompiles


@dataclass
class TileContext:
    """What the Database hands the tile executor for one table scan."""

    table_key: str
    dictionary: TableDictionary
    regions: list[Region]
    append_mode: bool = False


@dataclass
class _FileTileEntry:
    """Device tiles for one SST file, padded to TILE_QUANTUM at build time
    so repeated queries hand the SAME arrays to the compiled program."""

    cols: dict[str, jnp.ndarray] = field(default_factory=dict)
    nulls: dict[str, jnp.ndarray] = field(default_factory=dict)
    epochs: dict[str, int] = field(default_factory=dict)  # tag col -> dict epoch
    valid: jnp.ndarray | None = None
    num_rows: int = 0
    nbytes: int = 0


class TileCacheManager:
    """Device-resident per-(region, SST file) column tiles with LRU budget."""

    def __init__(self, budget_bytes: int = 8 << 30):
        self.budget = budget_bytes
        self._lock = threading.RLock()
        self._entries: OrderedDict[tuple[int, str], _FileTileEntry] = OrderedDict()
        self._used = 0
        self._region_versions: dict[int, int] = {}

    # ---- bookkeeping -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"files": len(self._entries), "bytes": self._used}

    def invalidate_region(self, region_id: int, keep_file_ids: set[str] | None = None):
        """Drop tiles of files no longer in the region's manifest."""
        with self._lock:
            for key in list(self._entries):
                if key[0] == region_id and (
                    keep_file_ids is None or key[1] not in keep_file_ids
                ):
                    self._used -= self._entries.pop(key).nbytes
            self._region_versions.pop(region_id, None)

    def invalidate_region_if_changed(
        self, region_id: int, keep_file_ids: set[str], manifest_version: int
    ):
        """Version-gated sweep: the O(cache) scan only runs when the
        region's manifest actually advanced since the last query."""
        with self._lock:
            if self._region_versions.get(region_id) == manifest_version:
                return
        self.invalidate_region(region_id, keep_file_ids)
        with self._lock:
            self._region_versions[region_id] = manifest_version

    def _evict_locked(self, pinned: set[tuple[int, str]]):
        while self._used > self.budget and len(self._entries) > len(pinned):
            for key in list(self._entries):
                if key not in pinned:
                    self._used -= self._entries.pop(key).nbytes
                    metrics.TILE_CACHE_EVICTIONS.inc()
                    break
            else:
                break

    # ---- tile build / fetch ------------------------------------------------
    def file_tiles(
        self,
        region: Region,
        dictionary: TableDictionary,
        meta: FileMeta,
        tag_cols: list[str],
        ts_col: str | None,
        value_cols: list[str],
        pinned: set[tuple[int, str]],
    ) -> _FileTileEntry | None:
        """Cached (or freshly built) device tiles for one SST file.  Returns
        None when the file cannot be tiled (e.g. a needed column is absent —
        pre-ALTER files fall back to the scan path)."""
        key = (region.region_id, meta.file_id)
        need = list(dict.fromkeys(tag_cols + ([ts_col] if ts_col else []) + value_cols))
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is None:
            entry = _FileTileEntry(num_rows=meta.num_rows)
        missing = [c for c in need if c not in entry.cols]
        if missing:
            built = self._build_columns(
                region, dictionary, meta, missing, tag_cols, ts_col
            )
            if built is None:
                return None
            cols, nulls, epochs, nbytes, pad = built
            if entry.valid is None:
                v = np.zeros(pad, bool)
                v[: entry.num_rows] = True
                entry.valid = jnp.asarray(v)
                nbytes += pad
            entry.cols.update(cols)
            entry.nulls.update(nulls)
            entry.epochs.update(epochs)
            entry.nbytes += nbytes
            metrics.TILE_CACHE_MISSES.inc()
            with self._lock:
                old = self._entries.pop(key, None)
                if old is not None and old is not entry:
                    self._used -= old.nbytes
                self._entries[key] = entry
                self._used += nbytes
                self._evict_locked(pinned)
        else:
            metrics.TILE_CACHE_HITS.inc()
        return entry

    def repair_entries(
        self,
        entries: list[_FileTileEntry],
        dictionary: TableDictionary,
        tag_cols: list[str],
    ):
        """Dictionary-growth repair: one gather per stale tag column.  MUST
        run after every source of the query has updated the dictionary
        (a later file/memtable can insert values that shift codes an
        earlier-fetched tile was encoded with).  Serialized under the cache
        lock so concurrent queries can't double-apply a permutation."""
        with self._lock:
            for entry in entries:
                for tag in tag_cols:
                    if tag not in entry.epochs:
                        continue
                    perm = dictionary.perm_since(tag, entry.epochs[tag])
                    if perm is not None:
                        entry.cols[tag] = jnp.take(
                            jnp.asarray(perm),
                            entry.cols[tag],
                            mode="fill",
                            fill_value=-1,
                        ).astype(jnp.int32)
                    entry.epochs[tag] = dictionary.epoch

    def _build_columns(
        self,
        region: Region,
        dictionary: TableDictionary,
        meta: FileMeta,
        columns: list[str],
        tag_cols: list[str],
        ts_col: str | None,
    ):
        table = region.sst_reader.read(meta, None, columns=columns)
        if table.num_rows != meta.num_rows:
            return None  # unexpected — refuse rather than mis-aggregate
        for name in columns:
            if name not in table.column_names:
                return None  # file predates the column (ALTER) — not tileable
        return _encode_table_tiles(dictionary, table, columns, tag_cols, ts_col)


def _encode_table_tiles(
    dictionary: TableDictionary,
    table: pa.Table,
    columns: list[str],
    tag_cols: list[str],
    ts_col: str | None,
):
    """Shared encode-and-pad for SST files and memtable tails: tag strings
    -> dictionary codes (growing the dictionary), ts -> int64, values ->
    numeric; everything zero-padded to TILE_QUANTUM and uploaded.  Returns
    (cols, nulls, epochs, nbytes, pad) or None when a column can't tile."""
    n = table.num_rows
    pad = padded_size(n, TILE_QUANTUM)
    cols: dict[str, jnp.ndarray] = {}
    nulls: dict[str, jnp.ndarray] = {}
    epochs: dict[str, int] = {}
    nbytes = 0
    for name in columns:
        col = table[name]
        if name in tag_cols:
            dictionary.update(name, col)
            np_arr = dictionary.encode(name, col)
            epochs[name] = dictionary.epoch
        elif name == ts_col:
            np_arr = np.asarray(
                pc.cast(col, pa.int64()).to_numpy(zero_copy_only=False)
            )
        else:
            np_arr = _value_to_numpy(col)
            if np_arr is None:
                return None
            if col.null_count:
                present = np.zeros(pad, bool)
                present[:n] = np.asarray(
                    pc.is_valid(col).to_numpy(zero_copy_only=False), bool
                )
                nulls[name] = jnp.asarray(present)
                nbytes += present.nbytes
        padded = np.zeros(pad, dtype=np_arr.dtype)
        padded[:n] = np_arr
        arr = jnp.asarray(padded)
        cols[name] = arr
        nbytes += arr.nbytes
    return cols, nulls, epochs, nbytes, pad


def _value_to_numpy(col) -> np.ndarray | None:
    t = col.type
    if pa.types.is_dictionary(t):
        col = pc.cast(col, t.value_type)
        t = t.value_type
    if not (pa.types.is_floating(t) or pa.types.is_integer(t) or pa.types.is_boolean(t)):
        return None
    arr = col.to_numpy(zero_copy_only=False)
    if arr.dtype == object:
        arr = np.array([0 if v is None else v for v in arr], dtype=np.float64)
    elif np.issubdtype(arr.dtype, np.floating):
        arr = np.nan_to_num(arr, nan=0.0)
    elif arr.dtype == bool:
        arr = arr.astype(np.float32)
    return arr


# ---- the single-dispatch program -------------------------------------------


@functools.lru_cache(maxsize=64)
@functools.lru_cache(maxsize=256)
def _tile_program(plan: DistGroupByPlan, nullable_cols: tuple[str, ...]):
    """jit program: per-source partial states, merged pairwise, FINALIZED on
    device, and packed into ONE [K, G] float64 buffer holding ONLY the rows
    this query's output consumes — one dispatch in, one device->host
    transfer out.  On a remote-device harness every separate fetch pays the
    full host round-trip, so everything rides one buffer (counts are exact
    in float64 below 2^53), and bytes scale with requested outputs, not
    with every state the kernels track.

    Count rows ship only for (a) explicit count() outputs and (b) NULLABLE
    aggregated columns (NULL-group gating); non-nullable columns gate on
    the single presence row.  Returns (fn, layout)."""
    per_col_aggs: dict[str, set] = {}
    for func, col in plan.agg_specs:
        per_col_aggs.setdefault(col, set()).add(_FUNC_TO_KERNEL[func])
    layout: list[tuple[str, str]] = [("__presence", "count")]
    for col, aggs in per_col_aggs.items():
        for agg in sorted(aggs):
            if agg == "count":
                continue  # handled below
            layout.append((col, agg))
        if "count" in aggs or (col in nullable_cols and col != COUNT_STAR):
            layout.append((col, "count"))

    # FIXED-SHAPE chunked dispatch, merges folded on device — NOT one jit
    # over a Python loop of all sources: tracing that loop unrolls the
    # program proportionally to SST count, and XLA compile time explodes
    # with data size (observed: minutes at TSBS scale).  Instead every
    # source is sliced into chunks of exactly CHUNK rows (sources are
    # power-of-two padded, so chunks tile them evenly; smaller sources keep
    # their own pow2 shape) — ONE compiled partial program serves any
    # dataset size, survives in the persistent compilation cache, and the
    # fold costs one tiny merge dispatch per chunk (~dispatch-floor each).
    partial_jit = jax.jit(functools.partial(compute_partial_states, plan))
    merge_jit = jax.jit(lambda a, b: {k: merge_states(a[k], b[k]) for k in a})

    def _final(merged):
        outs = {
            col: finalize(merged[col], tuple(sorted(aggs | {"count"})))
            for col, aggs in per_col_aggs.items()
        }
        outs["__presence"] = {"count": merged["__presence"].counts}
        rows = [outs[col][agg].astype(jnp.float64) for col, agg in layout]
        return jnp.stack(rows)

    final_jit = jax.jit(_final)

    from ..ops.tiles import DEFAULT_TILE_ROWS as _CHUNK

    def run(sources, dyn):
        merged = None
        for cols, valid, nulls in sources:
            n = int(valid.shape[0])
            step = _CHUNK if n > _CHUNK else n
            for start in range(0, n, step):
                c = {k: a[start : start + step] for k, a in cols.items()}
                v = valid[start : start + step]
                u = {k: a[start : start + step] for k, a in nulls.items()}
                states = partial_jit(c, v, u, dyn)
                merged = states if merged is None else merge_jit(merged, states)
        return final_jit(merged)

    return run, tuple(layout)


class TileExecutor:
    """Aggregation over cached HBM tiles; returns None when not applicable
    so the caller can fall back to the authoritative path."""

    def __init__(self, cache: TileCacheManager, config):
        self.cache = cache
        self.config = config

    # -- public entry --------------------------------------------------------
    def execute(self, lowering, schema, time_bounds, ctx: TileContext):
        t0 = time.perf_counter()
        out = self._try_execute(lowering, schema, time_bounds, ctx)
        if out is not None:
            metrics.TILE_QUERY_ELAPSED.observe(time.perf_counter() - t0)
        return out

    def _try_execute(self, lowering, schema, time_bounds, ctx: TileContext):
        scan = lowering.scan
        ts_name = schema.time_index.name if schema.time_index else None
        tag_cols = list(lowering.group_tags)
        # tag-typed filter columns also need code tiles
        tag_names = {c.name for c in schema.tag_columns()}
        filter_tag_cols = [
            f[0] for f in scan.filters if f[0] in tag_names and f[0] not in tag_cols
        ]
        all_tag_cols = tag_cols + filter_tag_cols
        value_cols = list(
            dict.fromkeys(
                [c for _f, c in lowering.agg_specs if c is not None]
                + [
                    f[0]
                    for f in scan.filters
                    if f[0] not in tag_names and f[0] != ts_name
                ]
            )
        )
        needs_ts = (
            lowering.bucket is not None
            or any(f == "last_value" for f, _ in lowering.agg_specs)
            or scan.time_range is not None
            or any(f[0] == ts_name for f in scan.filters)
        )
        use_ts = ts_name if (needs_ts and ts_name) else None

        # 1. snapshot + safety gate, pinning every region until dispatch
        # done.  The table's dictionary gate serializes the whole
        # epoch-sensitive section (tile fetch -> repair -> memtable encode
        # -> plan build -> arg pack): without it a concurrent query could
        # grow the dictionary and repair SHARED tile entries between our
        # phases, mixing code epochs inside one dispatch.
        pinned_regions: list[Region] = []
        with ctx.dictionary.table_lock:
            try:
                return self._locked_execute(
                    lowering, schema, scan, ctx, time_bounds, pinned_regions,
                    ts_name, tag_names, tag_cols, all_tag_cols, value_cols, use_ts,
                )
            finally:
                for region in pinned_regions:
                    region.unpin_scan()

    def _locked_execute(
        self, lowering, schema, scan, ctx, time_bounds, pinned_regions,
        ts_name, tag_names, tag_cols, all_tag_cols, value_cols, use_ts,
    ):
        if True:  # structure kept flat for readability of the phases below
            sources_meta = []  # (region, FileMeta|None mem marker, mem table)
            prune_pred = ScanPredicate(
                time_range=scan.time_range,
                filters=[f for f in scan.filters if f[0] in tag_names],
            )
            ranges: list[tuple[int, int]] = []
            for region in ctx.regions:
                region.pin_scan()
                pinned_regions.append(region)
                all_files, mems, version = region.tile_snapshot()
                # drop cached tiles of files compaction removed — but only
                # when the manifest actually changed since the last sweep
                self.cache.invalidate_region_if_changed(
                    region.region_id, {m.file_id for m in all_files}, version
                )
                files = region.sst_reader.prune_files(all_files, prune_pred)
                for meta in files:
                    if meta.num_deletes != 0:
                        return None  # tombstones (or unknown) -> dedup needed
                    sources_meta.append((region, meta, None))
                    ranges.append(meta.time_range)
                for mem in mems:
                    mem_table = mem.scan(
                        scan.time_range, dedup=not ctx.append_mode
                    )
                    if mem_table.num_rows == 0:
                        continue
                    if OP_COL in mem_table.column_names:
                        op = pc.fill_null(
                            pc.cast(mem_table[OP_COL], pa.int64()), 0
                        )
                        if pc.sum(op).as_py():
                            return None  # tombstones in memtable
                        mem_table = mem_table.drop_columns([OP_COL])
                    sources_meta.append((region, None, mem_table))
                    if ts_name and ts_name in mem_table.column_names:
                        ts_i = pc.cast(mem_table[ts_name], pa.int64())
                        ranges.append(
                            (pc.min(ts_i).as_py(), pc.max(ts_i).as_py())
                        )
                    else:
                        ranges.append((0, 0))
            if not ctx.append_mode and not _disjoint(ranges):
                return None
            if not sources_meta:
                return None  # empty table: let the normal path shape output

            # 2. fetch/build file tiles + encode memtable tails
            pinned_keys = {
                (r.region_id, m.file_id) for r, m, _ in sources_meta if m is not None
            }
            # phase A: grow the dictionary from every source BEFORE any
            # encode whose output must be final — memtable values first
            # (cheap), then file builds (which update as they encode)
            for _region, meta, mem_table in sources_meta:
                if meta is None:
                    ctx.dictionary.update_table(mem_table, all_tag_cols)
            file_entries: list[_FileTileEntry] = []
            slots: list = []
            for region, meta, mem_table in sources_meta:
                if meta is not None:
                    entry = self.cache.file_tiles(
                        region, ctx.dictionary, meta, all_tag_cols,
                        use_ts, value_cols, pinned_keys,
                    )
                    if entry is None:
                        return None
                    file_entries.append(entry)
                    slots.append(entry)
                else:
                    slots.append((region, mem_table))
            # phase B: the dictionary is final for this query — repair any
            # tile encoded under an older epoch with one gather, and encode
            # the memtable tails against the final code assignment
            self.cache.repair_entries(file_entries, ctx.dictionary, all_tag_cols)
            device_sources = []
            for s in slots:
                if isinstance(s, _FileTileEntry):
                    device_sources.append((s.cols, s.valid, s.nulls))
                else:
                    src = self._encode_mem(
                        ctx.dictionary, s[1], all_tag_cols, use_ts, value_cols
                    )
                    if src is None:
                        return None
                    device_sources.append(src)

            # 3. the static plan (cards AFTER all dictionary updates) plus
            # its runtime-dynamic parameters (filter literals, bucket
            # geometry) — changing a literal or window reuses the compile
            built = self._build_plan(
                lowering, schema, scan, ctx, tag_cols, time_bounds, use_ts
            )
            if built is None:
                return None
            plan, dyn_host = built
            if plan.num_groups > self.config.max_groups * 64:
                return None  # group space too large for dense [G] states

            # 4. one dispatch
            nullable_cols = tuple(
                sorted(
                    c
                    for _f, c in plan.agg_specs
                    if c != COUNT_STAR
                    and schema.has_column(c)
                    and schema.column(c).nullable
                )
            )
            program, layout = _tile_program(plan, nullable_cols)
            need_cols = self._plan_cols(plan)
            args = []
            for cols, valid, nulls in device_sources:
                args.append(
                    (
                        {k: v for k, v in cols.items() if k in need_cols},
                        valid,
                        {k: v for k, v in nulls.items() if k in need_cols},
                    )
                )
            dyn = {
                "filter_values": tuple(dyn_host["filter_values"]),
                "bucket_origin": np.int64(dyn_host["bucket_origin"]),
                "bucket_interval": np.int64(dyn_host["bucket_interval"]),
            }
            packed = program(tuple(args), dyn)
            metrics.TILE_LOWERED_TOTAL.inc()
            return self._finalize(
                packed, layout, plan, lowering, schema, ctx, dyn_host
            )

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _plan_cols(plan: DistGroupByPlan) -> set:
        need = set(plan.group_tags) | {f[0] for f in plan.filters}
        if plan.bucket_col:
            need.add(plan.bucket_col)
        if plan.ts_col:
            need.add(plan.ts_col)
        for _f, c in plan.agg_specs:
            if c != COUNT_STAR:
                need.add(c)
        return need

    def _encode_mem(self, dictionary, table, tag_cols, ts_col, value_cols):
        """Encode the (small, fresh) memtable tail; same encode-and-pad as
        file tiles (_encode_table_tiles) so the two can never diverge."""
        need = list(
            dict.fromkeys(tag_cols + ([ts_col] if ts_col else []) + value_cols)
        )
        for name in need:
            if name not in table.column_names:
                return None
        built = _encode_table_tiles(dictionary, table, need, tag_cols, ts_col)
        if built is None:
            return None
        cols, nulls, _epochs, _nbytes, pad = built
        v = np.zeros(pad, bool)
        v[: table.num_rows] = True
        return (cols, jnp.asarray(v), nulls)

    def _build_plan(self, lowering, schema, scan, ctx, tag_cols, time_bounds, use_ts):
        """Returns (plan, dyn_host): `plan` is the compile-static structure
        (filter literals replaced by placeholders, n_buckets quantized to a
        power of two) and `dyn_host` carries the runtime values — so
        dashboards that vary literals or time windows reuse one compile."""
        d = ctx.dictionary
        if lowering.bucket is not None:
            ts_col, interval, origin_hint = lowering.bucket
            if scan.time_range is not None and scan.time_range[0] > -(1 << 61) and scan.time_range[1] < (1 << 61):
                lo, hi = scan.time_range
            else:
                lo, hi = time_bounds()
                hi += 1
            unit_ns = schema.time_index.data_type.timestamp_unit_ns()
            interval_native = max(int(interval * 1_000_000) // max(unit_ns, 1), 1)
            origin = origin_hint + ((lo - origin_hint) // interval_native) * interval_native
            n_buckets = max(int((hi - origin + interval_native - 1) // interval_native), 1)
            n_buckets = _quantize_card(n_buckets)
            bucket_col = ts_col
        else:
            bucket_col, interval_native, origin, n_buckets = None, 1, 0, 1

        # filters: tag values -> sorted codes (order-preserving, so even
        # inequalities translate); time range -> explicit ts filters.
        # Structure (name, op, arity) is static; values ride `dyn`.
        ts_name = schema.time_index.name if schema.time_index else None
        tag_names = {c.name for c in schema.tag_columns()}
        enc_filters: list[tuple[str, str, object]] = []
        filter_vals: list = []

        def push(name, op, value, dtype):
            if op in ("in", "not in"):
                enc_filters.append((name, op, len(value)))
                filter_vals.append(tuple(dtype(v) for v in value))
            else:
                enc_filters.append((name, op, None))
                filter_vals.append(dtype(value))

        for name, op, value in scan.filters:
            if name in tag_names:
                f = _encode_tag_filter(d, name, op, value)
                if f is None:
                    return None
                for fname, fop, fval in f:
                    push(fname, fop, fval, np.int32)
            else:
                if isinstance(value, str):
                    from ..datatypes.coercion import coerce_string_scalar

                    # numeric literal as string (prepared statements)
                    v = coerce_string_scalar(value, pa.float64())
                    value = v.as_py() if isinstance(v, pa.Scalar) else v
                    if isinstance(value, str):
                        return None
                dtype = np.int64 if name == ts_name else np.float64
                push(name, op, value, dtype)
        if scan.time_range is not None and use_ts:
            lo, hi = scan.time_range
            if lo > -(1 << 61):
                push(use_ts, ">=", int(lo), np.int64)
            if hi < (1 << 61):
                push(use_ts, "<", int(hi), np.int64)

        norm_specs = []
        for func, col in lowering.agg_specs:
            norm_specs.append((func, COUNT_STAR if col is None else col))
        needs_ts_order = any(f == "last_value" for f, _ in norm_specs)
        filter_null_cols = tuple(
            sorted(
                {
                    name
                    for name, _op, _v in enc_filters
                    if name not in tag_names
                    and name != ts_name
                    and schema.has_column(name)
                    and schema.column(name).nullable
                }
            )
        )
        plan = DistGroupByPlan(
            group_tags=tuple(tag_cols),
            tag_cards=tuple(_quantize_card(d.cardinality(t)) for t in tag_cols),
            bucket_col=bucket_col,
            bucket_origin=0,  # dynamic — see dyn_host
            bucket_interval=1,
            n_buckets=n_buckets,
            agg_specs=tuple(norm_specs),
            filters=tuple(enc_filters),
            acc_dtype=self.config_acc_dtype(),
            ts_col=use_ts if needs_ts_order else None,
            filter_null_cols=filter_null_cols,
        )
        dyn_host = {
            "filter_values": filter_vals,
            "bucket_origin": origin,
            "bucket_interval": interval_native,
        }
        return plan, dyn_host

    def config_acc_dtype(self) -> str:
        import jax as _jax

        return "float64" if _jax.config.jax_enable_x64 else "float32"

    def _finalize(self, packed, layout, plan, lowering, schema, ctx, dyn_host):
        # ONE host fetch total, regardless of how many aggregates ran
        flat = np.asarray(packed)
        finals: dict[str, dict[str, np.ndarray]] = {}
        for i, (col, agg) in enumerate(layout):
            finals.setdefault(col, {})[agg] = flat[i]
        outputs: dict[str, np.ndarray] = {}
        presence = finals["__presence"]["count"]
        non_empty = presence > 0
        for func, col in plan.agg_specs:
            out = finals[col]
            kernel = _FUNC_TO_KERNEL[func]
            arr = np.asarray(out[kernel])
            # NULL gating: nullable columns carry their own count row;
            # non-nullable columns have count == presence by construction
            col_count = out.get("count", presence)
            if col == COUNT_STAR:
                outputs["count(*)"] = arr.astype(np.int64)
            elif func == "count":
                outputs[f"count({col})"] = arr.astype(np.int64)
            else:
                outputs[f"{func}({col})"] = np.where(col_count > 0, arr, np.nan)
        tag_values = {t: ctx.dictionary.values(t) for t in plan.group_tags}
        result = GroupByResult(
            outputs=outputs,
            non_empty=non_empty,
            tag_values=tag_values,
            plan=plan,
            bucket_origin=dyn_host["bucket_origin"],
            bucket_interval=dyn_host["bucket_interval"],
        )
        return result.to_table()


def _encode_tag_filter(
    d: TableDictionary, name: str, op: str, value
) -> list[tuple[str, str, object]] | None:
    """Translate a tag-string predicate to code space.  Sorted codes make
    inequalities exact; a null slot (always the max code) must be excluded
    from every operator except '=' (SQL: NULL never satisfies a filter)."""
    null_code = d.code_of(name, None)
    guard = [(name, "!=", null_code)] if null_code >= 0 else []
    if op == "=":
        return [(name, "=", d.code_of(name, value))]
    if op == "!=":
        return guard + [(name, "!=", d.code_of(name, value))]
    if op == "in":
        return guard + [(name, "in", tuple(d.code_of(name, v) for v in value))]
    if op == "not in":
        return guard + [(name, "not in", tuple(d.code_of(name, v) for v in value))]
    if op == "<":
        return guard + [(name, "<", d.bound(name, value))]
    if op == ">=":
        return guard + [(name, ">=", d.bound(name, value))]
    if op == "<=":
        return guard + [(name, "<", d.bound_right(name, value))]
    if op == ">":
        return guard + [(name, ">=", d.bound_right(name, value))]
    return None


def _disjoint(ranges: list[tuple[int, int]]) -> bool:
    """True when every pair of inclusive [lo, hi] ranges is non-overlapping."""
    if len(ranges) <= 1:
        return True
    s = sorted(ranges)
    for (alo, ahi), (blo, bhi) in zip(s, s[1:]):
        if ahi >= blo:
            return False
    return True
